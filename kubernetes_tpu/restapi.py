"""REST registry over the hub — the kube-apiserver's resource surface
(SURVEY §1 layer 2: pkg/master + pkg/registry + the generic apiserver),
serving the slice of the v1 API this framework's clients consume.

The storage semantics come from the hub itself (kubernetes_tpu/sim.py is
the etcd3+registry analog: global revision, per-object resourceVersion,
CAS bindings, watch history with compaction); this module is the HTTP
facade the reference builds in staging/src/k8s.io/apiserver:

- GET    /api/v1/pods                         list (all namespaces)
- GET    /api/v1/namespaces/{ns}/pods         list (one namespace)
- POST   /api/v1/namespaces/{ns}/pods         create (admission → 403)
- GET    /api/v1/namespaces/{ns}/pods/{name}  read
- DELETE /api/v1/namespaces/{ns}/pods/{name}  delete
- POST   /api/v1/namespaces/{ns}/pods/{name}/binding
         the Binding subresource — the scheduler's one write
         (registry/core/pod/storage/storage.go:154 BindingREST.Create);
         409 Conflict on the CAS failures assignPod surfaces
- GET    /api/v1/nodes[/{name}], POST /api/v1/nodes, DELETE, PUT
         PUT enforces the resourceVersion precondition the way
         GuaranteedUpdate does (etcd3/store.go:236): stale rv → 409
- GET    /api/v1/[namespaces/{ns}/]{services|endpoints|events}
         read-only lists of the dataplane kinds and the Event registry
         (the events-recorder writes land here as API objects)
- GET    /api/v1/watch/{pods|nodes}?resourceVersion=N
         NDJSON event drain from the hub's watch history; a compacted
         rv → 410 Gone with reason=Expired (the client relists, exactly
         client-go Reflector's "too old resource version" path)

Status errors use the metav1.Status shape so a client-go-style consumer
can switch on reason/code.
"""

from __future__ import annotations

import collections
import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from kubernetes_tpu.admission import AdmissionError
from kubernetes_tpu.api.selectors import (
    SelectorError,
    match_fields,
    match_labels,
    node_fields,
    parse_field_selector,
    parse_label_selector,
    pod_fields,
    validate_field_keys,
)
from kubernetes_tpu.auth import (
    ALLOW,
    Attributes,
    Unauthenticated,
    forbidden_message,
)
from kubernetes_tpu.extender import node_to_json, pod_to_json
from kubernetes_tpu.grpc_shim import node_from_json
from kubernetes_tpu.server import pod_from_json
from kubernetes_tpu.sim import Compacted, Conflict, HollowCluster


class AuditLog:
    """Request-level audit trail — the apiserver audit subsystem's shape
    (staging/src/k8s.io/apiserver/pkg/audit: policy level, one event per
    request at ResponseComplete) over this facade.

    Levels mirror audit.Level: ``"None"`` drops everything, ``"Metadata"``
    records verb/resource/code/latency, ``"Request"`` additionally keeps
    the request body. Entries land in a bounded ring (the in-memory
    backend) and optionally stream to ``sink`` (the log-backend seam —
    a callable per JSON-able entry dict)."""

    def __init__(self, level: str = "Metadata", capacity: int = 1024,
                 sink=None) -> None:
        if level not in ("None", "Metadata", "Request"):
            raise ValueError(f"unknown audit level {level!r}")
        self.level = level
        self.capacity = capacity
        self.sink = sink
        self.entries: "collections.deque" = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def record(self, verb: str, path: str, code: int, latency_s: float,
               body=None, user=None) -> None:
        if self.level == "None":
            return
        entry = {
            "stage": "ResponseComplete",
            "verb": verb,
            "requestURI": path,
            "code": code,
            "latency_s": round(latency_s, 6),
        }
        if user is not None:
            # audit events carry the authenticated identity
            # (apis/audit/types.go Event.User)
            entry["user"] = {"username": user.name,
                             "groups": list(user.groups)}
        if self.level == "Request" and body is not None:
            entry["requestObject"] = body
        with self._lock:
            self.entries.append(entry)
        if self.sink is not None:
            try:
                self.sink(entry)
            except Exception:
                # a failing log backend must never fail (or noise up) the
                # request it audits; the ring entry is already stored
                pass


#: the facade's resource surface, the single source for discovery AND the
#: OpenAPI document (name, kind, namespaced, verbs) — apiserver publishes
#: the same table through /api/v1 APIResourceList
#: (pkg/endpoints/discovery/resources) and /openapi/v2
#: (pkg/server/routes/openapi.go:30)
RESOURCES = (
    ("pods", "Pod", True,
     ("create", "delete", "get", "list", "patch", "watch")),
    ("pods/binding", "Binding", True, ("create",)),
    ("pods/eviction", "Eviction", True, ("create",)),
    ("nodes", "Node", False,
     ("create", "delete", "get", "list", "patch", "update", "watch")),
    ("namespaces", "Namespace", False, ("create", "delete", "get", "list")),
    ("services", "Service", True, ("list", "watch")),
    ("endpoints", "Endpoints", True, ("list", "watch")),
    ("events", "Event", True, ("list", "watch")),
    ("serviceaccounts", "ServiceAccount", True, ("list",)),
    ("configmaps", "ConfigMap", True, ("get", "list")),
)


#: non-core groups this facade serves READ-ONLY (writes go through the
#: hub seams that own them): coordination/v1 Leases make HA state
#: API-observable; apps/v1 Deployments+ReplicaSets make rollout state
#: observable (`kubectl get deploy` / `rollout status`). Controller
#: objects live hub-side without namespaces; they present in "default",
#: where their pods run.
LEASE_GROUP = "coordination.k8s.io"
APPS_GROUP = "apps"
CERT_GROUP = "certificates.k8s.io"
GROUPS = {
    LEASE_GROUP: (("leases", "Lease", True, ("get", "list")),),
    APPS_GROUP: (("deployments", "Deployment", True,
                  ("create", "delete", "get", "list", "patch", "update")),
                 ("deployments/scale", "Scale", True, ("get", "update")),
                 ("replicasets", "ReplicaSet", True, ("get", "list")),
                 ("daemonsets", "DaemonSet", True, ("get", "list")),
                 ("statefulsets", "StatefulSet", True, ("get", "list")),
                 ("controllerrevisions", "ControllerRevision", True,
                  ("list",))),
    CERT_GROUP: (("certificatesigningrequests",
                  "CertificateSigningRequest", False, ("get", "list")),),
}
#: group -> served version (the reference serves certificates at
#: v1beta1 in this cycle — csr.go's capi group)
GROUP_VERSIONS = {CERT_GROUP: "v1beta1"}
GROUP_RESOURCES = GROUPS[LEASE_GROUP]  # back-compat alias


def lease_to_json(ns: str, name: str, record, rv: int) -> dict:
    """coordination/v1 Lease wire shape from the stored election record
    (resourcelock.LeaderElectionRecord fields -> LeaseSpec names,
    leaselock.go:120 LeaderElectionRecordToLeaseSpec)."""
    return {
        "metadata": {"name": name, "namespace": ns,
                     "resourceVersion": str(rv)},
        "spec": {
            "holderIdentity": record.holder_identity,
            "leaseDurationSeconds": record.lease_duration_s,
            "acquireTime": record.acquire_time,
            "renewTime": record.renew_time,
            "leaseTransitions": record.leader_transitions,
        },
    }


def api_resource_list() -> dict:
    """GET /api/v1 — APIResourceList (discovery/resources analog)."""
    return {
        "kind": "APIResourceList",
        "apiVersion": "v1",
        "groupVersion": "v1",
        "resources": [
            {"name": name, "kind": kind, "namespaced": namespaced,
             "verbs": list(verbs)}
            for name, kind, namespaced, verbs in RESOURCES
        ],
    }


def openapi_doc() -> dict:
    """GET /openapi/v2 — a real (if minimal) swagger 2.0 document derived
    from the same RESOURCES table the routes implement, so the published
    surface can never drift from the served one. Operations carry the
    x-kubernetes-action the reference stamps (routes/openapi.go serves
    the aggregated spec; this facade's is hand-rolled but live)."""
    verb_http = {"create": "post", "delete": "delete", "get": "get",
                 "list": "get", "update": "put", "patch": "patch"}
    paths: dict = {}
    for name, kind, namespaced, verbs in RESOURCES:
        base, _, sub = name.partition("/")
        collection = (f"/api/v1/namespaces/{{namespace}}/{base}"
                      if namespaced else f"/api/v1/{base}")
        item = collection + "/{name}" + (f"/{sub}" if sub else "")
        for verb in verbs:
            if verb == "watch":
                route, method, action = f"/api/v1/watch/{base}", "get", "watch"
            elif verb == "create":
                # a SUBRESOURCE create posts to the item path
                # (/pods/{name}/binding); only base-resource creates post
                # to the collection
                route = item if sub else collection
                method, action = "post", "create"
            elif verb == "list":
                route, method, action = collection, "get", "list"
            else:
                route, method, action = item, verb_http[verb], verb
            op = {
                "x-kubernetes-action": action,
                "x-kubernetes-group-version-kind":
                    {"group": "", "version": "v1", "kind": kind},
                "responses": {"200": {"description": "OK"},
                              "401": {"description": "Unauthorized"}},
            }
            paths.setdefault(route, {})[method] = op
    # the non-core groups' routes (same verb->route mapping as the core
    # table; subresource names like "deployments/scale" route to the
    # item path)
    for group, resources in GROUPS.items():
        gv = GROUP_VERSIONS.get(group, "v1")
        for name, kind, namespaced, verbs in resources:
            gbase = f"/apis/{group}/{gv}"
            res, _, sub = name.partition("/")
            collection = (f"{gbase}/namespaces/{{namespace}}/{res}"
                          if namespaced else f"{gbase}/{res}")
            item = collection + "/{name}" + (f"/{sub}" if sub else "")
            gvk = {"group": group, "version": gv, "kind": kind}
            ok = {"200": {"description": "OK"},
                  "401": {"description": "Unauthorized"}}
            for verb in verbs:
                if verb == "list":
                    routes = ({f"{gbase}/{res}", collection}
                              if namespaced else {collection})
                    for route in sorted(routes):
                        paths.setdefault(route, {})["get"] = {
                            "x-kubernetes-action": "list",
                            "x-kubernetes-group-version-kind": gvk,
                            "responses": ok}
                    continue
                route = collection if verb == "create" and not sub else item
                paths.setdefault(route, {})[verb_http[verb]] = {
                    "x-kubernetes-action": verb,
                    "x-kubernetes-group-version-kind": gvk,
                    "responses": ok}
    return {
        "swagger": "2.0",
        "info": {"title": "kubernetes_tpu", "version": "v1"},
        "paths": paths,
        "definitions": {
            "v1.Status": {"type": "object", "properties": {
                "kind": {"type": "string"},
                "apiVersion": {"type": "string"},
                "status": {"type": "string"},
                "reason": {"type": "string"},
                "message": {"type": "string"},
                "code": {"type": "integer"},
            }},
        },
    }


#: RFC-1123 DNS label — the apiserver's namespace/name validation
#: (apimachinery validation.IsDNS1123Label); anything else (slashes,
#: uppercase, 64+ chars) would mint objects no item route can address
_DNS_LABEL = re.compile(r"^[a-z0-9]([-a-z0-9]{0,61}[a-z0-9])?$")


def ns_to_json(hub, ns) -> dict:
    """The one v1.Namespace document shape (phase is live controller
    state), used by every namespace handler."""
    return _with_rv({
        "metadata": {"name": ns.name},
        "status": {"phase": ns.phase},
    }, hub, f"namespaces/{ns.name}")


def _rs_bound(hub, rs) -> int:
    """ONE bound-pod predicate for every apps/v1 doc shape (and the same
    rule the rolling reconcile's availability math uses)."""
    return sum(1 for k in rs.live
               if k in hub.truth_pods and hub.truth_pods[k].node_name)


def apps_rs_doc(hub, rs) -> dict:
    rv = {"resourceVersion": str(hub._revision)}
    return {
        "metadata": {"name": rs.name, "namespace": "default", **rv,
                     **({"ownerReferences": [
                         {"kind": "Deployment", "name": rs.owner}]}
                        if rs.owner else {})},
        "spec": {"replicas": rs.replicas},
        "status": {"replicas": len(rs.live),
                   "readyReplicas": _rs_bound(hub, rs),
                   "revision": rs.revision},
    }


def apps_deploy_doc(hub, d) -> dict:
    """v1.Deployment wire shape (deployment_controller syncStatus
    counts). The spec carries the WRITABLE slice round-trippably —
    template resources under spec.template so a merge patch of the
    template drives a rollout the way patching the pod template image
    does in the reference."""
    owned = [rs for rs in hub.replicasets.values() if rs.owner == d.name]
    new_rs = hub.replicasets.get(d.rs_name())
    return {
        "metadata": {"name": d.name, "namespace": "default",
                     "resourceVersion": str(hub._revision)},
        "spec": {
            "replicas": d.replicas,
            "strategy": d.strategy,
            "maxSurge": d.max_surge,
            "maxUnavailable": d.max_unavailable,
            "template": {"cpuMilli": d.cpu_milli, "memory": d.memory,
                         "priority": d.priority},
        },
        "status": {
            "observedRevision": d.template_rev,
            "replicas": sum(len(rs.live) for rs in owned),
            "updatedReplicas": (_rs_bound(hub, new_rs) if new_rs else 0),
            "readyReplicas": sum(_rs_bound(hub, rs) for rs in owned),
        },
    }


def apps_scale_doc(hub, d) -> dict:
    """autoscaling/v1 Scale — the /scale subresource document
    (pkg/registry/apps/deployment/storage/storage.go:230 ScaleREST):
    spec.replicas is the write surface HPA and kubectl scale drive."""
    owned = [rs for rs in hub.replicasets.values() if rs.owner == d.name]
    return {
        "kind": "Scale", "apiVersion": "autoscaling/v1",
        "metadata": {"name": d.name, "namespace": "default",
                     "resourceVersion": str(hub._revision)},
        "spec": {"replicas": d.replicas},
        "status": {"replicas": sum(len(rs.live) for rs in owned),
                   "selector": f"app={d.name}"},
    }


def svc_to_doc(hub, key: str, svc) -> dict:
    """v1.Service wire doc — one builder for lists AND watch frames."""
    s_ns, name = key.split("/", 1)
    return _with_rv({
        "metadata": {"name": name, "namespace": s_ns},
        "spec": {
            "selector": dict(svc.selector),
            "clusterIP": svc.cluster_ip,
            "ports": [
                # v1 defaulting: targetPort falls back to port
                # (the apiserver's service defaulting)
                {"port": p.port,
                 "targetPort": p.target_port or p.port,
                 "protocol": p.protocol,
                 **({"nodePort": p.node_port} if p.node_port else {})}
                for p in svc.ports
            ],
            "sessionAffinity": svc.session_affinity,
            "type": getattr(svc, "type", "ClusterIP"),
        },
        **({"status": {"loadBalancer": {"ingress": [
            {"ip": svc.load_balancer_ingress}]}}}
           if getattr(svc, "load_balancer_ingress", "") else {}),
    }, hub, f"services/{key}")


def _ep_target_ref(a) -> dict:
    a_ns, a_name = a.pod_key.split("/", 1)
    return {"kind": "Pod", "name": a_name, "namespace": a_ns}


def ep_to_doc(hub, key: str, ep) -> dict:
    """v1.Endpoints wire doc — one builder for lists AND watch frames.
    An Endpoints with no addresses at all serializes ``subsets: []``
    (the reference drops empty subsets, it never emits a subset whose
    address lists are both empty)."""
    e_ns, name = key.split("/", 1)
    subsets = []
    if ep.ready or ep.not_ready:
        subsets = [{
            "addresses": [
                {"nodeName": a.node_name, "targetRef": _ep_target_ref(a)}
                for a in ep.ready
            ],
            "notReadyAddresses": [
                {"targetRef": _ep_target_ref(a)} for a in ep.not_ready
            ],
        }]
    return _with_rv({
        "metadata": {"name": name, "namespace": e_ns},
        "subsets": subsets,
    }, hub, f"endpoints/{key}")


def event_to_doc(hub, key: str, ev) -> dict:
    """v1.Event wire doc — one builder for lists AND watch frames."""
    ev_ns, name = key.split("/", 1)
    return _with_rv({
        "metadata": {"name": name, "namespace": ev_ns},
        "involvedObject": {
            "kind": getattr(ev, "involved_kind", "Pod"),
            "namespace": ev.object_key.split("/", 1)[0],
            "name": ev.object_key.split("/", 1)[1],
        },
        "type": ev.type,
        "reason": ev.reason,
        "message": ev.message,
        "count": ev.count,
        "firstTimestamp": ev.first_timestamp,
        "lastTimestamp": ev.last_timestamp,
    }, hub, f"events/{key}")


def status_doc(code: int, reason: str, message: str) -> dict:
    return {
        "kind": "Status",
        "apiVersion": "v1",
        "status": "Failure",
        "reason": reason,
        "message": message,
        "code": code,
    }


def _with_rv(doc: dict, hub: HollowCluster, obj_key: str) -> dict:
    doc.setdefault("metadata", {})["resourceVersion"] = str(
        hub.resource_version.get(obj_key, 0)
    )
    return doc


class ListOptions:
    """The server-evaluated slice of metav1.ListOptions (types.go:322):
    labelSelector, fieldSelector, limit, continue. Parsed once per list
    request; selector errors surface as 400 the way the apiserver's
    option-decoding does."""

    def __init__(self, query: dict) -> None:
        self.label = parse_label_selector(
            (query.get("labelSelector") or [""])[0])
        self.field = parse_field_selector(
            (query.get("fieldSelector") or [""])[0])
        try:
            self.limit = int((query.get("limit") or ["0"])[0])
        except ValueError:
            raise SelectorError("limit must be an integer")
        if self.limit < 0:
            raise SelectorError("limit must be non-negative")
        self.cont = (query.get("continue") or [""])[0]

    def matches(self, labels, fields) -> bool:
        return (match_labels(self.label, labels)
                and match_fields(self.field, fields))


def foreign_keys(doc, canon) -> list:
    """Key paths present in ``doc`` that its canonical re-serialization
    ``canon`` does not carry — i.e., fields OUTSIDE the wire projection.
    A patch introducing such a field must be rejected, never silently
    dropped (the projection would swallow it and the semantic-equality
    check would wave the patch through)."""
    out = []
    if isinstance(doc, dict) and isinstance(canon, dict):
        for k, v in doc.items():
            if k not in canon:
                out.append(k)
            else:
                out.extend(f"{k}.{p}" for p in foreign_keys(v, canon[k]))
    elif isinstance(doc, list) and isinstance(canon, list):
        for i, (a, b) in enumerate(zip(doc, canon)):
            out.extend(f"[{i}].{p}" for p in foreign_keys(a, b))
        if len(doc) > len(canon):
            out.append(f"[{len(canon)}:]")
    return out


def merge_patch(target, patch):
    """RFC 7386 JSON Merge Patch — the semantics behind
    Content-Type: application/merge-patch+json
    (apiserver/pkg/endpoints/handlers/patch.go:59 PatchResource,
    jsonmergepatch path): objects merge recursively, null DELETES the
    key, everything else replaces."""
    if not isinstance(patch, dict):
        return patch
    if not isinstance(target, dict):
        target = {}
    out = dict(target)
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = merge_patch(out.get(k), v)
    return out


def encode_continue(rv: int, last_key: str) -> str:
    """Opaque continuation token (pager contract,
    apiserver/pkg/storage/etcd3/store.go encodeContinue): carries the
    list revision and the key to resume AFTER."""
    import base64

    raw = json.dumps({"rv": rv, "start": last_key}).encode()
    return base64.urlsafe_b64encode(raw).decode().rstrip("=")


def decode_continue(token: str):
    """-> (rv, start_after_key); raises SelectorError on garbage."""
    import base64

    try:
        pad = "=" * (-len(token) % 4)
        doc = json.loads(base64.urlsafe_b64decode(token + pad))
        return int(doc["rv"]), str(doc["start"])
    except Exception:
        raise SelectorError("invalid continue token")


class RestServer:
    """Serve the hub's registry over HTTP. ``serve()`` returns the bound
    port; ``close()`` shuts down."""

    #: how many revisions of history the server keeps alive for poll-
    #: watchers (the watch cache's bounded event window — cacher.go keeps
    #: a capacity-bounded cyclic buffer so watchers survive etcd
    #: compaction for a while; beyond it they get 410 and relist)
    WATCH_WINDOW = 2000

    #: per-watcher send-buffer bound: more events than this pending for
    #: one poll-watcher means it fell too far behind — it is answered
    #: 410 Gone (relist) instead of the hub serializing an unbounded
    #: drain under its lock (serving/fairness.py WatchHub semantics,
    #: adapted to the stateless poll-watch)
    WATCH_MAX_DRAIN = 4096

    def __init__(self, hub: HollowCluster, host: str = "127.0.0.1",
                 port: int = 0, audit: "AuditLog | None" = None,
                 authn=None, authz=None, fairness=None,
                 watch_max_drain: "int | None" = None,
                 metrics=None, fault_injector=None) -> None:
        """``authn``/``authz`` install the reference's request filter
        chain in its order — authentication, then authorization, then
        the handler (admission runs inside create paths), per
        DefaultBuildHandlerChain (apiserver pkg/server/config.go:639).
        ``authn=None`` (default) keeps the facade open — the reference's
        --anonymous-auth + AlwaysAllow development posture. ``authz``
        defaults to AlwaysAllow when only ``authn`` is given.

        ``fairness`` (a serving.fairness.FlowController) installs the
        APF-style admission filter AHEAD of the chain: requests are
        classified into flow schemas (exempt/watch/readonly/mutating),
        seats are bounded per flow with a bounded FIFO of waiters, and
        overload answers 429 TooManyRequests + Retry-After instead of
        piling up handler threads (the reference's priority-and-fairness
        filter position, config.go WithPriorityAndFairness)."""
        self.hub = hub
        self.audit = audit
        self.fairness = fairness
        #: faults.FaultInjector (or None): the NETWORK chaos seam —
        #: ``rest:{VERB}`` rules fire ahead of the filter chain.
        #: ``rpc_error`` answers 500 before the handler acts (definite
        #: failure); ``latency`` delays; ``rpc_timeout`` lets the
        #: handler run but kills the RESPONSE on the wire — the client
        #: sees a dead socket while the server-side state mutated, the
        #: exact ambiguity the scheduler's bind protocol must resolve.
        self.fault_injector = fault_injector
        self.watch_max_drain = (self.WATCH_MAX_DRAIN
                                if watch_max_drain is None
                                else int(watch_max_drain))
        #: watchers answered 410 for falling behind the drain bound
        self.watch_evictions = 0
        #: optional SchedulerMetrics — drives
        #: scheduler_watch_evictions_total (falls back to the fairness
        #: controller's attached metrics so one wiring covers both)
        self.metrics = metrics if metrics is not None else getattr(
            fairness, "metrics", None)
        if authz is not None and authn is None:
            # an authorizer without an authenticator would silently
            # enforce NOTHING (no identity to authorize) — refuse the
            # looks-configured-but-open posture outright
            raise ValueError(
                "authz requires authn (enable anonymous auth via "
                "TokenAuthenticator(tokens, anonymous=True) to authorize "
                "credential-less requests)"
            )
        self.authn = authn
        self.authz = authz
        # the anchor cursor pins the hub's auto-compaction floor so that
        # stateless HTTP watchers (transient cursors) can resume from an
        # rv they saw in an earlier poll; _trim (run on every request)
        # keeps the pin — and therefore retained history — bounded
        self._anchor = hub.watch(hub._revision)
        # serializes check-then-act mutations AND reads against the hub's
        # own mutators (step()/controllers run under hub.lock on the
        # driver thread): the CAS semantics and dict iterations must hold
        # across ThreadingHTTPServer handler threads and the sim loop
        self._lock = getattr(hub, "lock", None) or threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _drain_body(self) -> None:
                """Discard any unread request body before responding.
                An early rejection (404 on an unknown path, 401, bad
                verb) that never touched rfile leaves the POSTed body in
                the socket's receive buffer; closing the connection with
                unread data makes the kernel send RST instead of FIN,
                and the client's in-flight response read then fails with
                ECONNRESET — a timing-dependent flake the REST fuzz test
                catches. Bounded (1 MiB) so a hostile Content-Length
                cannot wedge a handler thread."""
                if getattr(self, "_body_read", False):
                    return
                self._body_read = True
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                except (TypeError, ValueError):
                    return
                if 0 < n <= 1 << 20:
                    try:
                        self.rfile.read(n)
                    except OSError:
                        pass

            def _send_raw(self, code: int, ctype: str, body: bytes,
                          headers=None) -> None:
                self._code = code  # for the audit trail
                self._drain_body()
                if getattr(self, "_buffer_mode", False):
                    # built under the hub lock, WRITTEN outside it — a
                    # slow client must never wedge the hub on socket I/O
                    self._buffered = (code, ctype, body, headers)
                    return
                self._write_response(code, ctype, body, headers)

            def _write_response(self, code, ctype, body, headers) -> None:
                if getattr(self, "_suppress_response", False):
                    # injected ambiguous timeout (rpc_timeout at the
                    # rest seam): the handler ran and the state
                    # mutated, but the answer dies on the wire — close
                    # without responding so the client observes exactly
                    # what a timed-out RPC observes
                    self.close_connection = True
                    return
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _flush_buffered(self) -> None:
                buffered, self._buffered = getattr(self, "_buffered", None), None
                self._buffer_mode = False
                if buffered is not None:
                    self._write_response(*buffered)

            def _respond(self, code: int, doc, headers=None) -> None:
                self._send_raw(code, "application/json",
                               json.dumps(doc).encode(), headers)

            def _fail(self, code: int, reason: str, message: str,
                      headers=None) -> None:
                self._respond(code, status_doc(code, reason, message),
                              headers)

            def do_GET(self):
                outer._begin(self)
                if outer._net_fault(self):
                    return
                t0 = time.perf_counter()
                seat = outer._admit(self, "GET")
                try:
                    if seat is None or not outer._auth(self, "GET"):
                        return
                    # reads hold the same lock as mutations (and as
                    # hub.step()): a list comprehension over a hub dict
                    # must never race a concurrent create/delete. The
                    # response is only BUFFERED under the lock; the socket
                    # write happens after release.
                    self._buffer_mode = True
                    with outer._lock:
                        outer._get(self)
                    self._flush_buffered()
                finally:
                    outer._release(seat)
                    outer._record_audit(self, "get", t0)

            def do_POST(self):
                outer._begin(self)
                if outer._net_fault(self):
                    return
                t0 = time.perf_counter()
                seat = outer._admit(self, "POST")
                try:
                    if seat is None or not outer._auth(self, "POST"):
                        return
                    with outer._lock:
                        outer._post(self)
                finally:
                    outer._release(seat)
                    outer._record_audit(self, "create", t0)

            def do_PUT(self):
                outer._begin(self)
                if outer._net_fault(self):
                    return
                t0 = time.perf_counter()
                seat = outer._admit(self, "PUT")
                try:
                    if seat is None or not outer._auth(self, "PUT"):
                        return
                    with outer._lock:
                        outer._put(self)
                finally:
                    outer._release(seat)
                    outer._record_audit(self, "update", t0)

            def do_DELETE(self):
                outer._begin(self)
                if outer._net_fault(self):
                    return
                t0 = time.perf_counter()
                seat = outer._admit(self, "DELETE")
                try:
                    if seat is None or not outer._auth(self, "DELETE"):
                        return
                    with outer._lock:
                        outer._delete(self)
                finally:
                    outer._release(seat)
                    outer._record_audit(self, "delete", t0)

            def do_PATCH(self):
                outer._begin(self)
                if outer._net_fault(self):
                    return
                t0 = time.perf_counter()
                seat = outer._admit(self, "PATCH")
                try:
                    if seat is None or not outer._auth(self, "PATCH"):
                        return
                    with outer._lock:
                        outer._patch(self)
                finally:
                    outer._release(seat)
                    outer._record_audit(self, "patch", t0)

        self._closed = False
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_port
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    def _net_fault(self, handler) -> bool:
        """Injected network fault for one request (site
        ``rest:{METHOD}``). Returns True when the request was fully
        answered here (``rpc_error`` → 500 before any handler state
        changed); ``latency`` sleeps then proceeds; ``rpc_timeout``
        marks the handler's RESPONSE for suppression and proceeds —
        the ambiguous class at the HTTP layer."""
        inj = self.fault_injector
        if inj is None:
            return False
        out = inj.rpc_hook(f"rest:{handler.command}")
        if out is None:
            return False
        kind, rule, _committed = out
        if kind == "rpc_error":
            handler._fail(500, "InternalError",
                          "injected rpc error (nothing committed)")
            return True
        if kind == "latency":
            time.sleep(min(max(rule.latency_s, 0.0), 1.0))
        elif kind == "rpc_timeout":
            handler._suppress_response = True
        return False

    def serve(self) -> int:
        self._thread.start()

        def trim_loop():
            # request-driven trimming alone would pin the hub's
            # compaction floor forever on an idle server; this keeps the
            # retained history bounded regardless of traffic
            while not self._closed:
                self._trim()
                time.sleep(1.0)

        self._trimmer = threading.Thread(target=trim_loop, daemon=True,
                                         name="rest-watch-trim")
        self._trimmer.start()
        return self.port

    def _trim(self) -> None:
        """Advance the compaction pin AND enforce it, keeping at most
        ~WATCH_WINDOW revisions of history alive regardless of request
        mix. Moving only the anchor (the pre-serving behavior) merely
        ALLOWED a sim-driven ``hub.step()`` to compact; a REST-only hub
        never stepped, so sustained churn grew the watch history without
        bound — the compaction now happens here, batched (one sweep per
        WATCH_WINDOW/8 revisions) so a hot request path never pays an
        O(history) filter per request. Watchers that fall behind the
        floor get the clean 410 Gone + relist answer from ``_watch``,
        never a silently truncated drain. This deliberately overrides
        the hub's slowest-open-cursor auto-compaction (sim.step): an
        in-process cursor (Reflector) lagging more than WATCH_WINDOW
        revisions gets Compacted and relists — the reference's bounded
        watch cache makes exactly that trade, and relist-on-Compacted
        is the Reflector contract."""
        pin = self.hub._revision - self.WATCH_WINDOW
        self._anchor.rev = max(self._anchor.rev, pin)
        if pin - self.hub._compacted_rev >= max(self.WATCH_WINDOW // 8, 1):
            with self._lock:
                self.hub.compact(pin)

    def _admit(self, h, http_verb: str):
        """APF-style admission, ahead of authn (the filter-chain slot of
        WithPriorityAndFairness): classify into a flow schema, take a
        seat (bounded FIFO wait), or answer 429 + Retry-After. Returns
        the seat to pass to :meth:`_release` — "" when no fairness
        filter is installed, None when the request was shed."""
        if self.fairness is None:
            return ""
        from kubernetes_tpu.serving.fairness import RequestRejected

        flow = self.fairness.classify(http_verb, h.path)
        try:
            return self.fairness.acquire(flow)
        except RequestRejected as e:
            h._fail(429, "TooManyRequests", str(e),
                    headers={"Retry-After":
                             str(max(int(round(e.retry_after_s)), 1))})
            return None

    def _release(self, seat) -> None:
        if seat and self.fairness is not None:
            self.fairness.release(seat)

    def _begin(self, h) -> None:
        """Per-request entry: trim history and clear per-request handler
        state — on a keep-alive connection the handler INSTANCE is reused,
        so stale _code/_audit_body from the previous request would be
        audited for the next one."""
        self._trim()
        h._code = 0
        h._audit_body = None
        h._user = None
        h._body_read = False  # this request's body not yet consumed

    def _auth(self, h, http_verb: str) -> bool:
        """The authentication -> authorization filter pair, ahead of all
        handler logic (WithAuthentication/WithAuthorization,
        endpoints/filters/authentication.go:41, authorization.go:42).
        Returns False after sending the Status-shaped 401/403."""
        if self.authn is None:
            return True
        try:
            user = self.authn.authenticate(h.headers)
        except Unauthenticated as e:
            h._fail(401, "Unauthorized", str(e))
            return False
        h._user = user
        verb, resource, ns, name = self.request_info(http_verb, h.path)
        attrs = Attributes(
            user=user, verb=verb, resource=resource, namespace=ns,
            name=name,
            # non-resource request (discovery/openapi/version): carry the
            # raw path for NonResourceURLs rules
            path="" if resource else h.path.split("?", 1)[0].rstrip("/"),
        )
        authz = self.authz
        if authz is not None and authz.authorize(attrs) != ALLOW:
            h._fail(403, "Forbidden", forbidden_message(attrs))
            return False
        return True

    @staticmethod
    def request_info(http_verb: str, path: str):
        """(verb, resource, namespace, name) for authorization — the
        RequestInfo resolver (endpoints/request/requestinfo.go:158):
        POSITIONAL segments only, GET on an exact collection route is
        "list", "watch" only as the segment after the version prefix,
        subresources join the resource as "pods/binding" (the rbac/v1
        resource spelling). Group-routed paths
        (/apis/coordination.k8s.io/v1/...) resolve the same way — the
        RBAC resource name carries no group prefix."""
        p = path.split("?", 1)[0]
        seg = RestServer._route(p)
        if seg is None:
            routed = RestServer._route_group(p)
            seg = routed[1] if routed is not None else None
        verb = {"GET": "get", "POST": "create", "PUT": "update",
                "DELETE": "delete", "PATCH": "patch"}.get(
                    http_verb, http_verb.lower())
        if not seg:
            return verb, "", "", ""
        if seg[0] == "watch":
            return "watch", seg[1] if len(seg) > 1 else "", "", ""
        ns = name = ""
        resource, rest = seg[0], seg[1:]
        if seg[0] == "namespaces" and len(seg) >= 3:
            ns, resource, rest = seg[1], seg[2], seg[3:]
        if rest:
            name = rest[0]
            if len(rest) >= 2:
                resource = f"{resource}/{rest[1]}"
        elif verb == "get":
            verb = "list"
        return verb, resource, ns, name

    def _record_audit(self, h, verb: str, t0: float) -> None:
        if self.audit is None:
            return
        path = h.path
        if verb == "get":
            # one resolver for audit AND authorization (request_info):
            # positional RequestInfo semantics — "watch" only right after
            # the version prefix, "list" only for nameless collections
            verb = self.request_info("GET", path)[0]
        self.audit.record(verb, path, getattr(h, "_code", 0),
                          time.perf_counter() - t0,
                          body=getattr(h, "_audit_body", None),
                          user=getattr(h, "_user", None))

    def close(self) -> None:
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- routing helpers ----------------------------------------------------

    @staticmethod
    def _route(path: str):
        """Split '/api/v1/...' into segments after the version."""
        parts = [p for p in path.split("/") if p]
        if parts[:2] != ["api", "v1"]:
            return None
        return parts[2:]

    @staticmethod
    def _route_group(path: str):
        """Split '/apis/<group>/v1/...' into segments after the
        group-version (the apiserver's group routing layer) for any
        served group. Returns (group, segments) or None."""
        parts = [p for p in path.split("/") if p]
        if (len(parts) >= 3 and parts[0] == "apis" and parts[1] in GROUPS
                and parts[2] == GROUP_VERSIONS.get(parts[1], "v1")):
            return parts[1], parts[3:]
        return None

    @staticmethod
    def _read_body(h):
        """Parsed JSON body, or None (after a 400 response) on garbage."""
        n = int(h.headers.get("Content-Length", 0))
        raw = h.rfile.read(n) or b"{}"
        h._body_read = True  # _drain_body must not read the socket again
        try:
            doc = json.loads(raw)
        except ValueError:
            h._fail(400, "BadRequest", "request body is not valid JSON")
            return None
        if not isinstance(doc, dict):
            h._fail(400, "BadRequest", "request body must be a JSON object")
            return None
        h._audit_body = doc  # Request-level audit keeps the object
        return doc

    # -- GET ----------------------------------------------------------------

    def _get(self, h) -> None:
        url = urlparse(h.path)
        path = url.path.rstrip("/")
        # discovery + OpenAPI (nonResourceURLs in the reference's terms):
        # /api -> APIVersions, /api/v1 -> APIResourceList,
        # /openapi/v2 -> the live swagger doc, /version -> version info
        if path == "/api":
            return h._respond(200, {"kind": "APIVersions",
                                    "versions": ["v1"]})
        if path == "/api/v1":
            return h._respond(200, api_resource_list())
        if path == "/apis":
            return h._respond(200, {
                "kind": "APIGroupList",
                "groups": [{
                    "name": g,
                    "versions": [{
                        "groupVersion":
                            f"{g}/{GROUP_VERSIONS.get(g, 'v1')}",
                        "version": GROUP_VERSIONS.get(g, "v1")}],
                    "preferredVersion": {
                        "groupVersion":
                            f"{g}/{GROUP_VERSIONS.get(g, 'v1')}",
                        "version": GROUP_VERSIONS.get(g, "v1")},
                } for g in sorted(GROUPS)],
            })
        for g, resources in GROUPS.items():
            gv = GROUP_VERSIONS.get(g, "v1")
            if path == f"/apis/{g}/{gv}":
                return h._respond(200, {
                    "kind": "APIResourceList",
                    "groupVersion": f"{g}/{gv}",
                    "resources": [
                        {"name": name, "kind": kind,
                         "namespaced": namespaced, "verbs": list(verbs)}
                        for name, kind, namespaced, verbs in resources
                    ],
                })
        routed = self._route_group(url.path)
        if routed is not None:
            group, gseg = routed
            if group == LEASE_GROUP:
                return self._get_lease(h, gseg)
            if group == CERT_GROUP:
                return self._get_certs(h, gseg)
            return self._get_apps(h, gseg)
        if path == "/openapi/v2":
            return h._respond(200, openapi_doc())
        if path == "/version":
            from kubernetes_tpu import version_info

            return h._respond(200, version_info())
        seg = self._route(url.path)
        hub = self.hub
        if not seg:
            return h._fail(404, "NotFound", h.path)
        if seg[0] == "watch":
            return self._watch(h, seg[1:], parse_qs(url.query))
        if seg == ["nodes"]:
            from kubernetes_tpu.api.protobuf import node_list_to_pb

            return self._serve_list(
                h, parse_qs(url.query), "NodeList",
                list(hub.truth_nodes.values()),
                node_fields, lambda n: n.labels,
                lambda n: _with_rv(node_to_json(n), hub, f"nodes/{n.name}"),
                lambda n: n.name, to_pb_list=node_list_to_pb)
        if len(seg) == 2 and seg[0] == "nodes":
            n = hub.truth_nodes.get(seg[1])
            if n is None:
                return h._fail(404, "NotFound", f'nodes "{seg[1]}" not found')
            if self._wants_proto(h):
                from kubernetes_tpu.api.protobuf import (
                    PROTO_CONTENT_TYPE,
                    encode_envelope,
                    node_to_pb,
                )

                return h._send_raw(200, PROTO_CONTENT_TYPE,
                                   encode_envelope("Node", node_to_pb(n)))
            return h._respond(200, _with_rv(node_to_json(n), hub,
                                            f"nodes/{n.name}"))
        if seg[0] == "namespaces" and len(seg) <= 2:
            # namespace discovery reads (registry/core/namespace): the
            # lifecycle phase is live state — Terminating is what the
            # namespace controller is mid-draining
            if len(seg) == 1:
                return h._respond(200, {
                    "kind": "NamespaceList", "apiVersion": "v1",
                    "metadata": {"resourceVersion": str(hub._revision)},
                    "items": [ns_to_json(hub, n)
                              for n in hub.namespaces.values()],
                })
            n = hub.namespaces.get(seg[1])
            if n is None:
                return h._fail(404, "NotFound",
                               f'namespaces "{seg[1]}" not found')
            return h._respond(200, ns_to_json(hub, n))
        ns = None
        if seg[0] == "namespaces" and len(seg) >= 3:
            ns, seg = seg[1], seg[2:]
        if seg in (["services"], ["endpoints"]):
            # selector semantics mirror the watch side exactly (the
            # informer list+watch pair must accept identical options):
            # these kinds carry no labels, so a non-empty labelSelector
            # selects nothing; fields are metadata-only
            try:
                q = parse_qs(url.query)
                lsel = parse_label_selector(
                    (q.get("labelSelector") or [""])[0])
                fsel = parse_field_selector(
                    (q.get("fieldSelector") or [""])[0])
                match_fields(fsel, {"metadata.name": "probe",
                                    "metadata.namespace": "probe"})
            except SelectorError as e:
                return h._fail(400, "BadRequest", str(e))
            registry = (hub.services if seg == ["services"]
                        else hub.endpoints)
            to_doc = svc_to_doc if seg == ["services"] else ep_to_doc
            items = []
            for key, obj in sorted(registry.items()):
                k_ns, _, k_name = key.partition("/")
                if ns is not None and k_ns != ns:
                    continue
                if lsel and not match_labels(lsel, {}):
                    continue
                if fsel and not match_fields(fsel, {
                        "metadata.name": k_name,
                        "metadata.namespace": k_ns}):
                    continue
                items.append(to_doc(hub, key, obj))
            return h._respond(200, {
                "kind": ("ServiceList" if seg == ["services"]
                         else "EndpointsList"),
                "apiVersion": "v1",
                "metadata": {"resourceVersion": str(hub._revision)},
                "items": items,
            })
        if seg == ["events"]:
            from kubernetes_tpu.api.selectors import event_fields

            # field selectors (reason=..., involvedObject.name=... — the
            # kubectl --field-selector workflow); events carry no labels
            # so a labelSelector matches only when empty. Ordering stays
            # lastTimestamp (kubectl's newest-last), so the paginated
            # _serve_list pipeline (key-ordered) deliberately does not
            # serve this kind.
            try:
                fsel = parse_field_selector(
                    (parse_qs(url.query).get("fieldSelector") or [""])[0])
                validate_field_keys(fsel, "events")
                lsel = parse_label_selector(
                    (parse_qs(url.query).get("labelSelector") or [""])[0])
            except SelectorError as e:
                return h._fail(400, "BadRequest", str(e))
            items = []
            for key, ev in sorted(
                    getattr(hub, "events_v1", {}).items(),
                    key=lambda kv: kv[1].last_timestamp):
                ev_ns, name = key.split("/", 1)
                if ns is not None and ev_ns != ns:
                    continue
                if fsel and not match_fields(fsel, event_fields(key, ev)):
                    continue
                if lsel and not match_labels(lsel, {}):
                    continue
                items.append(event_to_doc(hub, key, ev))
            return h._respond(200, {
                "kind": "EventList", "apiVersion": "v1",
                "metadata": {"resourceVersion": str(hub._revision)},
                "items": items,
            })
        if seg == ["serviceaccounts"]:
            items = []
            for key in sorted(hub.service_accounts):
                sa_ns, name = key.split("/", 1)
                if ns is not None and sa_ns != ns:
                    continue
                items.append(_with_rv({
                    "metadata": {"name": name, "namespace": sa_ns},
                    # the tokens controller's credential, referenced the
                    # way v1 SAs reference their token secrets (names
                    # only — the secret VALUE never rides a list)
                    "secrets": [{"name": f"{name}-token"}],
                }, hub, f"serviceaccounts/{key}"))
            return h._respond(200, {
                "kind": "ServiceAccountList", "apiVersion": "v1",
                "metadata": {"resourceVersion": str(hub._revision)},
                "items": items,
            })
        if seg == ["configmaps"]:
            items = []
            for key in sorted(hub.configmaps):
                cm_ns, name = key.split("/", 1)
                if ns is not None and cm_ns != ns:
                    continue
                items.append(_with_rv({
                    "metadata": {"name": name, "namespace": cm_ns},
                    "data": dict(hub.configmaps[key].get("data", {})),
                }, hub, f"configmaps/{key}"))
            return h._respond(200, {
                "kind": "ConfigMapList", "apiVersion": "v1",
                "metadata": {"resourceVersion": str(hub._revision)},
                "items": items,
            })
        if (len(seg) == 2 and seg[0] == "configmaps" and ns is not None):
            key = f"{ns}/{seg[1]}"
            cm = hub.configmaps.get(key)
            if cm is None:
                return h._fail(404, "NotFound",
                               f'configmaps "{seg[1]}" not found')
            return h._respond(200, _with_rv({
                "metadata": {"name": seg[1], "namespace": ns},
                "data": dict(cm.get("data", {})),
            }, hub, f"configmaps/{key}"))
        if seg == ["pods"]:
            from kubernetes_tpu.api.protobuf import pod_list_to_pb

            return self._serve_list(
                h, parse_qs(url.query), "PodList",
                [p for p in hub.truth_pods.values()
                 if ns is None or p.namespace == ns],
                pod_fields, lambda p: p.labels,
                lambda p: _with_rv(pod_to_json(p), hub,
                                   f"pods/{p.key()}"),
                lambda p: p.key(), to_pb_list=pod_list_to_pb)
        if len(seg) == 2 and seg[0] == "pods" and ns is not None:
            p = hub.truth_pods.get(f"{ns}/{seg[1]}")
            if p is None:
                return h._fail(404, "NotFound", f'pods "{seg[1]}" not found')
            if self._wants_proto(h):
                from kubernetes_tpu.api.protobuf import (
                    PROTO_CONTENT_TYPE,
                    encode_envelope,
                    pod_to_pb,
                )

                return h._send_raw(200, PROTO_CONTENT_TYPE,
                                   encode_envelope("Pod", pod_to_pb(p)))
            return h._respond(200, _with_rv(pod_to_json(p), hub,
                                            f"pods/{p.key()}"))
        return h._fail(404, "NotFound", h.path)

    def _get_lease(self, h, seg) -> None:
        """Read-only Lease routes: list (all or one namespace) and get."""
        hub = self.hub

        def doc(key):
            ns, name = key.split("/", 1)
            return lease_to_json(
                ns, name, hub.leases[key],
                hub.resource_version.get(f"leases/{key}", 0))

        ns = None
        if seg[:1] == ["namespaces"] and len(seg) >= 3:
            ns, seg = seg[1], seg[2:]
        if seg == ["leases"]:
            items = [doc(key) for key in sorted(hub.leases)
                     if ns is None or key.split("/", 1)[0] == ns]
            return h._respond(200, {
                "kind": "LeaseList",
                "apiVersion": f"{LEASE_GROUP}/v1",
                "metadata": {"resourceVersion": str(hub._revision)},
                "items": items,
            })
        if len(seg) == 2 and seg[0] == "leases" and ns is not None:
            key = f"{ns}/{seg[1]}"
            if key not in hub.leases:
                return h._fail(404, "NotFound",
                               f'leases "{seg[1]}" not found')
            return h._respond(200, doc(key))
        return h._fail(404, "NotFound", h.path)

    def _get_certs(self, h, seg) -> None:
        """certificates.k8s.io/v1beta1 read routes: CSR list + get
        (cluster-scoped). The status carries the approval condition and
        whether a certificate was issued — the credential VALUE never
        rides a list (same rule as SA token secrets)."""
        hub = self.hub

        def doc(csr):
            conditions = []
            if csr.approved is True:
                conditions.append({"type": "Approved",
                                   "message": csr.approval_message})
            elif csr.approved is False:
                conditions.append({"type": "Denied",
                                   "message": csr.approval_message})
            return _with_rv({
                "metadata": {"name": csr.name},
                "spec": {
                    "username": csr.username,
                    "groups": list(csr.groups),
                    "usages": list(csr.usages),
                    "request": {"commonName": csr.request_cn,
                                "organizations": list(csr.request_orgs)},
                },
                "status": {
                    "conditions": conditions,
                    "certificateIssued": bool(csr.certificate),
                },
            }, hub, f"certificatesigningrequests/{csr.name}")

        if seg == ["certificatesigningrequests"]:
            return h._respond(200, {
                "kind": "CertificateSigningRequestList",
                "apiVersion": f"{CERT_GROUP}/v1beta1",
                "metadata": {"resourceVersion": str(hub._revision)},
                "items": [doc(hub.csrs[n]) for n in sorted(hub.csrs)],
            })
        if len(seg) == 2 and seg[0] == "certificatesigningrequests":
            csr = hub.csrs.get(seg[1])
            if csr is None:
                return h._fail(
                    404, "NotFound",
                    f'certificatesigningrequests "{seg[1]}" not found')
            return h._respond(200, doc(csr))
        return h._fail(404, "NotFound", h.path)

    def _get_apps(self, h, seg) -> None:
        """apps/v1 read routes: deployment + replicaset lists/gets (docs
        built by the module-level apps_*_doc helpers, shared with the
        write paths) plus the /scale subresource read. Controller objects
        are not individually versioned in the hub (hollow controllers
        mutate in place); item docs carry the GLOBAL revision so clients
        still get a usable change indicator."""
        hub = self.hub

        def rs_doc(rs):
            return apps_rs_doc(hub, rs)

        def deploy_doc(d):
            return apps_deploy_doc(hub, d)

        ns = None
        if seg[:1] == ["namespaces"] and len(seg) >= 3:
            ns, seg = seg[1], seg[2:]
        if ns not in (None, "default"):
            # controller objects live in "default" (module doc); other
            # namespaces legitimately have an EMPTY list of the KNOWN
            # kinds — but an unknown resource is 404, not a mislabeled
            # empty list
            empty_kinds = {"deployments": "DeploymentList",
                           "replicasets": "ReplicaSetList",
                           "daemonsets": "DaemonSetList",
                           "statefulsets": "StatefulSetList",
                           "controllerrevisions": "ControllerRevisionList"}
            if len(seg) == 1 and seg[0] in empty_kinds:
                return h._respond(200, {
                    "kind": empty_kinds[seg[0]],
                    "apiVersion": "apps/v1",
                    "metadata": {"resourceVersion": str(hub._revision)},
                    "items": []})
            return h._fail(404, "NotFound", h.path)
        def ds_doc(ds):
            live = [k for k in ds.live if k in hub.truth_pods]
            current = [k for k in live
                       if hub.truth_pods[k].labels.get("rev")
                       == str(ds.template_rev)]
            return _with_rv({
                "metadata": {"name": ds.name, "namespace": "default"},
                "spec": {
                    "updateStrategy": {"type": "RollingUpdate",
                                       "rollingUpdate": {"maxUnavailable":
                                                         ds.max_unavailable}},
                    "template": {"spec": {"nodeSelector":
                                          dict(ds.node_selector)}},
                },
                "status": {
                    "desiredNumberScheduled": len(ds.live),
                    "numberReady": sum(
                        1 for k in live if hub.truth_pods[k].node_name),
                    "updatedNumberScheduled": len(current),
                    "observedRevision": ds.template_rev,
                },
            }, hub, f"daemonsets/{ds.name}")

        def sts_doc(ss):
            pods = [p for p in hub.truth_pods.values()
                    if p.labels.get("ss") == ss.name]
            return _with_rv({
                "metadata": {"name": ss.name, "namespace": "default"},
                "spec": {
                    "replicas": ss.replicas,
                    "updateStrategy": {"type": "RollingUpdate",
                                       "rollingUpdate": {"partition":
                                                         ss.partition}},
                },
                "status": {
                    "replicas": len(pods),
                    "readyReplicas": sum(1 for p in pods if p.node_name),
                    "updatedReplicas": sum(
                        1 for p in pods
                        if p.labels.get("rev") == str(ss.template_rev)),
                    "observedRevision": ss.template_rev,
                },
            }, hub, f"statefulsets/{ss.name}")

        def cr_doc(cr):
            return _with_rv({
                "metadata": {"name": f"{cr.owner_name}-{cr.revision}",
                             "namespace": "default",
                             "ownerReferences": [{"kind": cr.owner_kind,
                                                  "name": cr.owner_name}]},
                "revision": cr.revision,
                "data": dict(cr.data),
            }, hub, f"controllerrevisions/{cr.key()}")

        if seg == ["controllerrevisions"]:
            items = [cr_doc(cr) for _, cr in
                     sorted(hub.controller_revisions.items())]
            return h._respond(200, {
                "kind": "ControllerRevisionList", "apiVersion": "apps/v1",
                "metadata": {"resourceVersion": str(hub._revision)},
                "items": items,
            })
        for kind, registry, doc, list_kind in (
                ("deployments", hub.deployments, deploy_doc,
                 "DeploymentList"),
                ("replicasets", hub.replicasets, rs_doc,
                 "ReplicaSetList"),
                ("daemonsets", hub.daemonsets, ds_doc, "DaemonSetList"),
                ("statefulsets", hub.statefulsets, sts_doc,
                 "StatefulSetList")):
            if seg == [kind]:
                return h._respond(200, {
                    "kind": list_kind,
                    "apiVersion": "apps/v1",
                    "metadata": {"resourceVersion": str(hub._revision)},
                    "items": [doc(o) for _, o in sorted(registry.items())],
                })
            if len(seg) == 2 and seg[0] == kind:
                obj = registry.get(seg[1])
                if obj is None:
                    return h._fail(404, "NotFound",
                                   f'{kind} "{seg[1]}" not found')
                return h._respond(200, doc(obj))
        if (len(seg) == 3 and seg[0] == "deployments"
                and seg[2] == "scale"):
            d = hub.deployments.get(seg[1])
            if d is None:
                return h._fail(404, "NotFound",
                               f'deployments "{seg[1]}" not found')
            return h._respond(200, apps_scale_doc(hub, d))
        return h._fail(404, "NotFound", h.path)

    @staticmethod
    def _wants_proto(h) -> bool:
        from kubernetes_tpu.api.protobuf import PROTO_CONTENT_TYPE

        return PROTO_CONTENT_TYPE in (h.headers.get("Accept") or "")

    def _serve_list(self, h, query, kind, objs, obj_fields, obj_labels,
                    to_json, key_of, to_pb_list=None) -> None:
        """One list pipeline for the selectable kinds: ListOptions parse →
        hub-side selector evaluation BEFORE any serialization (the watch
        cache's reason to exist — pod/strategy.go:197 MatchPod) → key-
        ordered limit/continue pagination (pager contract).

        Continuation fidelity: the reference serves every page of one
        list at the token's revision straight from etcd. This hub keeps
        only live truth + bounded watch history, so follow-up pages read
        CURRENT state after the token's resume key; the token's revision
        is still honored against the compaction floor — a token older
        than retained history gets 410 Expired exactly like the
        reference's "continue parameter is too old" path, telling the
        client to restart the list."""
        hub = self.hub
        try:
            opts = ListOptions(query)
            if opts.label or opts.field:
                selected = [o for o in objs
                            if opts.matches(obj_labels(o), obj_fields(o))]
            else:
                selected = list(objs)  # hot path: no per-object field dicts
        except SelectorError as e:
            return h._fail(400, "BadRequest", str(e))
        selected.sort(key=key_of)
        # the revision every page of THIS list reports and re-encodes:
        # continuation pages carry the ORIGINAL list revision forward
        # (the reference's continue token does the same) — re-stamping
        # with the current revision would let a slow pager outrun
        # compaction without ever seeing the 410 restart signal
        list_rv = hub._revision
        if opts.cont:
            try:
                list_rv, start = decode_continue(opts.cont)
            except SelectorError as e:
                return h._fail(400, "BadRequest", str(e))
            if list_rv < hub._compacted_rev:
                return h._fail(
                    410, "Expired",
                    "the provided continue parameter is too old to display "
                    "a consistent list result; restart the list without it")
            selected = [o for o in selected if key_of(o) > start]
        meta = {"resourceVersion": str(list_rv)}
        if opts.limit and len(selected) > opts.limit:
            remaining = len(selected) - opts.limit
            selected = selected[:opts.limit]
            meta["continue"] = encode_continue(list_rv,
                                               key_of(selected[-1]))
            if not (opts.label or opts.field):
                # ListMeta contract: remainingItemCount is OMITTED on
                # selector'd lists (the apiserver can't compute it
                # exactly there and leaves the field unset)
                meta["remainingItemCount"] = remaining
        if to_pb_list is not None and self._wants_proto(h):
            # Accept: application/vnd.kubernetes.protobuf — the typed
            # codec behind the k8s magic envelope (protobuf.go:95); the
            # big-list wire-efficiency path of the 50k-node story
            from kubernetes_tpu.api.protobuf import (
                PROTO_CONTENT_TYPE,
                encode_envelope,
            )

            msg = to_pb_list(selected, int(meta["resourceVersion"]))
            msg.continue_token = meta.get("continue", "")
            msg.remaining = meta.get("remainingItemCount", -1)
            return h._send_raw(200, PROTO_CONTENT_TYPE,
                               encode_envelope(kind, msg))
        return h._respond(200, {
            "kind": kind, "apiVersion": "v1", "metadata": meta,
            "items": [to_json(o) for o in selected],
        })

    # -- watch --------------------------------------------------------------

    def _watch(self, h, seg, query) -> None:
        """Drain currently-available events after ?resourceVersion as
        NDJSON and close — the chunked-frame watch with the client
        re-polling from its last seen rv (the cacher's delegation to
        etcd watch, compressed to a poll per request).

        ``labelSelector``/``fieldSelector`` scope the feed the way the
        cacher's watchFilterFunction does: matching ADDED/MODIFIED pass
        through, a MODIFIED whose new state no longer matches becomes a
        DELETED frame (the selector-scoped-feed contract informer caches
        rely on), non-matching ADDED are dropped.

        ``allowWatchBookmarks=true`` appends a final BOOKMARK frame
        carrying the revision this poll reached (cacher.go
        bookmarkAfterResourceVersion / watch_cache_interval): a watcher
        whose selector filters out all traffic still advances its
        anchor, so compaction of the quiet interval cannot 410 it into
        a full relist — exactly the reference's reason for bookmarks. One approximation vs
        the reference: the cacher tracks prevObject and suppresses
        DELETED frames for objects the watcher never matched; this
        stateless poll-watch cannot, so such frames may be sent — an
        informer cache ignores deletes of unknown keys, so the contract
        holds."""
        if seg not in (["pods"], ["nodes"], ["services"], ["endpoints"],
                       ["events"]):
            return h._fail(404, "NotFound", "/".join(seg))
        kind = seg[0]
        selectable = kind in ("pods", "nodes")
        try:
            rv = int((query.get("resourceVersion") or ["0"])[0])
        except ValueError:
            return h._fail(400, "BadRequest",
                           "resourceVersion must be an integer")
        try:
            lsel = parse_label_selector(
                (query.get("labelSelector") or [""])[0])
            fsel = parse_field_selector(
                (query.get("fieldSelector") or [""])[0])
            if selectable:
                validate_field_keys(fsel, kind)
            elif kind == "events":
                validate_field_keys(fsel, "events")
            else:
                # services/endpoints: metadata-only selectable fields
                # (strategy ToSelectableFields); unknown keys error at
                # request time like every other kind
                match_fields(fsel, {"metadata.name": "probe",
                                    "metadata.namespace": "probe"})
        except SelectorError as e:
            return h._fail(400, "BadRequest", str(e))

        from kubernetes_tpu.api.selectors import event_fields

        def selects(store_key, obj) -> bool:
            # label-less kinds (events/services/endpoints in this model)
            # match a labelSelector against {} — a non-empty selector
            # selects nothing, same as the list side and the reference's
            # semantics for unlabeled objects (never a 400: the standard
            # informer list+watch pair must accept identical options)
            if kind == "events":
                return (match_labels(lsel, {})
                        and match_fields(fsel, event_fields(store_key, obj)))
            if kind in ("services", "endpoints"):
                s_ns, _, s_name = store_key.partition("/")
                return (match_labels(lsel, {})
                        and match_fields(fsel, {
                            "metadata.name": s_name,
                            "metadata.namespace": s_ns}))
            fields = pod_fields(obj) if kind == "pods" else node_fields(obj)
            return (match_labels(lsel, obj.labels)
                    and match_fields(fsel, fields))

        if rv > self.hub._revision:
            # a future rv (stale client state from another hub
            # incarnation / a restored checkpoint) can never be served:
            # silently answering an empty drain would let the client
            # believe it is caught up at a revision this server has
            # never reached. 410 forces the clean relist the reference
            # reaches via its "too large resource version" timeout.
            return h._fail(
                410, "Expired",
                f"resourceVersion {rv} is ahead of this server "
                f"(current {self.hub._revision}); relist and re-watch "
                "from the returned resourceVersion")
        try:
            events = self.hub.watch(rv).poll()
        except Compacted:
            # the reference's exact wire text ("too old resource
            # version: requested (floor)") — client-go Reflectors key
            # their relist on it; a bare internal message would still be
            # a 410 but loses the hint
            return h._fail(
                410, "Expired",
                f"too old resource version: {rv} "
                f"({self.hub._compacted_rev})")
        matched = [e for e in events if e[1].startswith(kind + "/")]
        if len(matched) > self.watch_max_drain:
            # bounded per-watcher send buffer (serving/fairness.py
            # WatchHub semantics on the stateless poll-watch): a watcher
            # this far behind would serialize an unbounded drain under
            # the hub lock, stalling every other client — disconnect it
            # with the relist signal instead
            self.watch_evictions += 1
            if self.metrics is not None:
                self.metrics.watch_evictions.inc()
            return h._fail(
                410, "Expired",
                f"watcher too far behind: {len(matched)} pending events "
                f"exceed the {self.watch_max_drain}-event send buffer; "
                "relist and re-watch")
        lines = []
        for rev, obj_key, etype, obj in matched:
            rest = obj_key.split("/", 1)[1]
            if (lsel or fsel) and obj is not None:
                if not selects(rest, obj):
                    if etype == "ADDED":
                        continue  # never matched this watcher's scope
                    etype, obj = "DELETED", None  # left the selector
            if obj is None:
                # namespaced keys are "<kind>/ns/name" — a DELETED frame
                # must carry namespace and name separately or informer
                # caches keyed on (ns, name) never evict the entry
                if kind != "nodes" and "/" in rest:
                    ns, name = rest.split("/", 1)
                    meta = {"name": name, "namespace": ns}
                else:
                    meta = {"name": rest}
                meta["resourceVersion"] = str(rev)
                doc = {"metadata": meta}
            else:
                builder = {
                    "pods": lambda: pod_to_json(obj),
                    "nodes": lambda: node_to_json(obj),
                    "services": lambda: svc_to_doc(self.hub, rest, obj),
                    "endpoints": lambda: ep_to_doc(self.hub, rest, obj),
                    "events": lambda: event_to_doc(self.hub, rest, obj),
                }[kind]
                doc = builder()
                doc.setdefault("metadata", {})["resourceVersion"] = str(rev)
            lines.append(json.dumps({"type": etype, "object": doc}))
        if (query.get("allowWatchBookmarks") or ["false"])[0] in (
                "true", "1"):
            mark = events[-1][0] if events else self.hub._revision
            kind_name = {"pods": "Pod", "nodes": "Node",
                         "services": "Service", "endpoints": "Endpoints",
                         "events": "Event"}[kind]
            lines.append(json.dumps({
                "type": "BOOKMARK",
                "object": {"kind": kind_name,
                           "apiVersion": "v1",
                           "metadata": {"resourceVersion": str(mark)}},
            }))
        body = ("\n".join(lines) + ("\n" if lines else "")).encode()
        h._send_raw(200, "application/json;stream=watch", body)

    # -- POST ---------------------------------------------------------------

    # -- apps/v1 writes ------------------------------------------------------

    @staticmethod
    def _apps_ns_route(seg):
        """('deployments', name_or_None, sub_or_None, ns) for a
        namespaces-prefixed apps segment list, else None. Writes REQUIRE
        the namespaced form — the cluster-scoped spelling
        (/apis/apps/v1/deployments/NAME) is not a published write route
        and must 404, not silently mutate the default namespace."""
        if seg[:1] != ["namespaces"] or len(seg) < 3:
            return None
        ns, seg = seg[1], seg[2:]
        if not seg or seg[0] != "deployments":
            return None
        return (seg[0], seg[1] if len(seg) > 1 else None,
                seg[2] if len(seg) > 2 else None, ns)

    def _deployment_from_spec(self, name: str, spec: dict):
        """Writable-spec doc -> Deployment, with apps/v1 validation
        surfaced as ValueError (callers answer 422 Invalid). Every field
        that would otherwise blow up LATER inside hub.step()'s rolling
        reconcile — a remotely-triggered async crash — is validated
        HERE: replicas non-negative, budgets int-or-percent."""
        from kubernetes_tpu.sim import Deployment, _int_or_percent

        tmpl = spec.get("template") or {}
        replicas = int(spec.get("replicas", 1))
        if replicas < 0:
            raise ValueError("spec.replicas must be non-negative")
        for field in ("maxSurge", "maxUnavailable"):
            v = spec.get(field, 1)
            try:
                if _int_or_percent(v, max(replicas, 1),
                                   round_up=True) < 0:
                    raise ValueError
            except (ValueError, TypeError, AttributeError):
                raise ValueError(
                    f"spec.{field} must be a non-negative integer or "
                    f"percentage string, got {v!r}")
        return Deployment(
            name,
            replicas=replicas,
            cpu_milli=float(tmpl.get("cpuMilli", 100)),
            memory=float(tmpl.get("memory", 256 * 2**20)),
            priority=int(tmpl.get("priority", 0)),
            strategy=spec.get("strategy", "RollingUpdate"),
            max_surge=spec.get("maxSurge", 1),
            max_unavailable=spec.get("maxUnavailable", 1),
        )

    def _post_deployment(self, h, name, ns, body) -> None:
        hub = self.hub
        if ns != "default":
            return h._fail(
                422, "Invalid",
                "controller objects live in namespace \"default\" in this "
                "hub (module doc, restapi.py GROUPS)")
        if not name or not _DNS_LABEL.match(name):
            return h._fail(422, "Invalid",
                           "deployment metadata.name must be an RFC-1123 "
                           "DNS label")
        if name in hub.deployments:
            return h._fail(409, "AlreadyExists",
                           f'deployments "{name}" already exists')
        try:
            d = self._deployment_from_spec(name, body.get("spec") or {})
        except (ValueError, TypeError) as e:
            return h._fail(422, "Invalid", str(e))
        hub.add_deployment(d)
        return h._respond(201, apps_deploy_doc(hub, d))

    def _apply_deployment_spec(self, h, d, spec: dict) -> None:
        """Shared PUT/PATCH tail: validate the merged writable spec via a
        probe construction (the same __post_init__ rules a create runs),
        then apply — replicas through the scale seam, template changes
        through rollout() so the revision bumps exactly when the
        reference's getNewReplicaSet would."""
        hub = self.hub
        try:
            probe = self._deployment_from_spec(d.name, spec)
        except (ValueError, TypeError) as e:
            return h._fail(422, "Invalid", str(e))
        d.strategy = probe.strategy
        d.max_surge = probe.max_surge
        d.max_unavailable = probe.max_unavailable
        if probe.replicas != d.replicas:
            hub.scale_deployment(d.name, probe.replicas)
        if (probe.cpu_milli, probe.memory, probe.priority) != (
                d.cpu_milli, d.memory, d.priority):
            d.rollout(cpu_milli=probe.cpu_milli, memory=probe.memory,
                      priority=probe.priority)
        return h._respond(200, apps_deploy_doc(hub, d))

    def _post(self, h) -> None:
        url_path = urlparse(h.path).path
        routed = self._route_group(url_path)
        if routed is not None:
            group, gseg = routed
            body = self._read_body(h)
            if body is None:
                return
            r = self._apps_ns_route(gseg) if group == APPS_GROUP else None
            if r is not None and r[1] is None and r[2] is None:
                name = (body.get("metadata") or {}).get("name", "")
                return self._post_deployment(h, name, r[3], body)
            return h._fail(404, "NotFound", h.path)
        seg = self._route(url_path)
        hub = self.hub
        if not seg:
            return h._fail(404, "NotFound", h.path)
        body = self._read_body(h)
        if body is None:
            return  # 400 already sent
        if seg == ["nodes"]:
            node = node_from_json(body)
            if node.name in hub.truth_nodes:
                return h._fail(409, "AlreadyExists",
                               f'nodes "{node.name}" already exists')
            hub.add_node(node)
            return h._respond(201, _with_rv(node_to_json(node), hub,
                                            f"nodes/{node.name}"))
        if seg == ["namespaces"]:
            name = (body.get("metadata") or {}).get("name", "")
            if not name or not _DNS_LABEL.match(name):
                # a non-DNS-label name (slash, uppercase, 64+) would mint
                # an object no item route can ever address or delete
                return h._fail(
                    400, "BadRequest",
                    "namespace metadata.name must be an RFC-1123 DNS label")
            if name in hub.namespaces:
                return h._fail(409, "AlreadyExists",
                               f'namespaces "{name}" already exists')
            hub.add_namespace(name)
            return h._respond(201, ns_to_json(hub, hub.namespaces[name]))
        if seg[0] == "namespaces" and len(seg) >= 3:
            ns, seg = seg[1], seg[2:]
            if seg == ["pods"]:
                pod = pod_from_json(body)
                pod.namespace = ns
                if pod.key() in hub.truth_pods:
                    return h._fail(409, "AlreadyExists",
                                   f'pods "{pod.name}" already exists')
                try:
                    hub.create_pod(pod)
                except AdmissionError as e:
                    return h._fail(403, "Forbidden", str(e))
                # serialize the STORED object: admission may have rewritten
                # the pod (mutating plugins return a new copy) and the hub
                # assigned metadata.uid on that admitted copy, not ours
                stored = hub.truth_pods[pod.key()]
                return h._respond(201, _with_rv(pod_to_json(stored), hub,
                                                f"pods/{stored.key()}"))
            if len(seg) == 3 and seg[0] == "pods" and seg[2] == "eviction":
                # the Eviction subresource (eviction.go:147): PDB-guarded
                # graceful delete; an exhausted budget is 429
                # TooManyRequests, exactly the apiserver's answer
                key = f"{ns}/{seg[1]}"
                if key not in hub.truth_pods:
                    return h._fail(404, "NotFound",
                                   f'pods "{seg[1]}" not found')
                ok, msg = hub.evict_pod(key)
                if not ok:
                    return h._fail(429, "TooManyRequests", msg)
                return h._respond(201, status_doc(201, "", "")
                                  | {"status": "Success"})
            if len(seg) == 3 and seg[0] == "pods" and seg[2] == "binding":
                key = f"{ns}/{seg[1]}"
                pod = hub.truth_pods.get(key)
                if pod is None:
                    return h._fail(404, "NotFound",
                                   f'pods "{seg[1]}" not found')
                target = (body.get("target") or {}).get("name", "")
                if not target:
                    # the real apiserver validates the binding target
                    return h._fail(400, "BadRequest",
                                   "binding target.name is required")
                claimed_uid = (body.get("metadata") or {}).get("uid", pod.uid)
                import dataclasses
                try:
                    hub.confirm_binding(
                        dataclasses.replace(pod, uid=claimed_uid,
                                            node_name=""),
                        target,
                    )
                except Conflict as e:
                    return h._fail(409, "Conflict", str(e))
                return h._respond(201, status_doc(201, "", "")
                                  | {"status": "Success"})
        return h._fail(404, "NotFound", h.path)

    # -- PUT (GuaranteedUpdate CAS) -----------------------------------------

    def _put(self, h) -> None:
        url_path = urlparse(h.path).path
        routed = self._route_group(url_path)
        if routed is not None:
            group, gseg = routed
            r = self._apps_ns_route(gseg) if group == APPS_GROUP else None
            if r is None or r[1] is None:
                return h._fail(404, "NotFound", h.path)
            _, name, sub, ns = r
            d = self.hub.deployments.get(name) if ns == "default" else None
            if d is None:
                return h._fail(404, "NotFound",
                               f'deployments "{name}" not found')
            body = self._read_body(h)
            if body is None:
                return
            if sub == "scale":
                # the Scale subresource write — HPA's and kubectl
                # scale's contract (ScaleREST.Update, storage.go:230)
                try:
                    replicas = int((body.get("spec") or {})["replicas"])
                    if replicas < 0:
                        raise ValueError
                except (KeyError, TypeError, ValueError):
                    return h._fail(422, "Invalid",
                                   "scale spec.replicas must be a "
                                   "non-negative integer")
                self.hub.scale_deployment(name, replicas)
                return h._respond(200, apps_scale_doc(self.hub, d))
            if sub is not None:
                return h._fail(404, "NotFound", h.path)
            return self._apply_deployment_spec(h, d, body.get("spec") or {})
        seg = self._route(url_path)
        hub = self.hub
        if not seg or len(seg) != 2 or seg[0] != "nodes":
            return h._fail(404, "NotFound", h.path)
        cur = hub.truth_nodes.get(seg[1])
        if cur is None:
            return h._fail(404, "NotFound", f'nodes "{seg[1]}" not found')
        body = self._read_body(h)
        if body is None:
            return  # 400 already sent
        want_rv = str((body.get("metadata") or {}).get("resourceVersion", ""))
        cur_rv = str(hub.resource_version.get(f"nodes/{seg[1]}", 0))
        if want_rv and want_rv != cur_rv:
            return h._fail(
                409, "Conflict",
                f"Operation cannot be fulfilled on nodes \"{seg[1]}\": "
                f"object has been modified (rv {cur_rv}, submitted {want_rv})",
            )
        node = node_from_json(body)
        if node.name != seg[1]:
            return h._fail(400, "BadRequest", "name mismatch")
        hub._update_node(node)
        return h._respond(200, _with_rv(node_to_json(node), hub,
                                        f"nodes/{node.name}"))

    # -- PATCH (RFC 7386 JSON merge patch) -----------------------------------

    def _patch(self, h) -> None:
        """PatchResource (apiserver/pkg/endpoints/handlers/patch.go:59),
        merge-patch flavor only: the declarative update verb controllers
        and kubectl apply ride. Routes: pods (metadata/labels — identity
        and placement stay immutable, the Binding subresource owns
        nodeName), nodes, and apps/v1 deployments (whose spec patch can
        scale AND roll out — template changes bump the revision exactly
        like patching the pod template image in the reference).

        A patch body carrying metadata.resourceVersion is an optimistic
        concurrency precondition (409 on mismatch), same as PUT — for
        pods and nodes. Deployments are controller objects the hub does
        not individually version (their docs carry the GLOBAL revision
        as a change indicator only), so an rv precondition there cannot
        mean what the client intends; such a patch is rejected 400
        explicitly rather than silently dropping the precondition."""
        ctype = h.headers.get("Content-Type", "").split(";")[0].strip()
        if ctype != "application/merge-patch+json":
            return h._fail(
                415, "UnsupportedMediaType",
                "only application/merge-patch+json is supported "
                "(json-patch and strategic-merge-patch are not served)")
        hub = self.hub
        url_path = urlparse(h.path).path
        patch = self._read_body(h)
        if patch is None:
            return

        def rv_precondition_ok(obj_key: str) -> bool:
            want = (patch.get("metadata") or {}).get("resourceVersion")
            if want is None:
                return True
            cur_rv = str(hub.resource_version.get(obj_key, 0))
            if str(want) != cur_rv:
                h._fail(409, "Conflict",
                        f"Operation cannot be fulfilled on {obj_key}: "
                        f"object has been modified (rv {cur_rv}, "
                        f"submitted {want})")
                return False
            return True

        routed = self._route_group(url_path)
        if routed is not None:
            group, gseg = routed
            r = self._apps_ns_route(gseg) if group == APPS_GROUP else None
            if r is None or r[1] is None or r[2] is not None:
                return h._fail(404, "NotFound", h.path)
            _, name, _, ns = r
            d = hub.deployments.get(name) if ns == "default" else None
            if d is None:
                return h._fail(404, "NotFound",
                               f'deployments "{name}" not found')
            if (patch.get("metadata") or {}).get("resourceVersion") is not None:
                return h._fail(
                    400, "BadRequest",
                    "deployments are not individually versioned; "
                    "resourceVersion preconditions are not supported on "
                    "this resource")
            cur_spec = apps_deploy_doc(hub, d)["spec"]
            merged = merge_patch(cur_spec, patch.get("spec") or {})
            return self._apply_deployment_spec(h, d, merged)

        seg = self._route(url_path)
        if seg and len(seg) == 2 and seg[0] == "nodes":
            cur = hub.truth_nodes.get(seg[1])
            if cur is None:
                return h._fail(404, "NotFound",
                               f'nodes "{seg[1]}" not found')
            if not rv_precondition_ok(f"nodes/{seg[1]}"):
                return
            merged = merge_patch(node_to_json(cur), patch)
            try:
                node = node_from_json(merged)
            except Exception as e:  # type-invalid merged doc is a 422,
                return h._fail(422, "Invalid",  # never a dropped conn
                               f"patched node document is invalid: {e!r}")
            if node.name != seg[1]:
                return h._fail(422, "Invalid",
                               "metadata.name is immutable")
            hub._update_node(node)
            return h._respond(200, _with_rv(node_to_json(node), hub,
                                            f"nodes/{node.name}"))
        if (seg and len(seg) == 4 and seg[0] == "namespaces"
                and seg[2] == "pods"):
            ns, name = seg[1], seg[3]
            key = f"{ns}/{name}"
            cur = hub.truth_pods.get(key)
            if cur is None:
                return h._fail(404, "NotFound", f'pods "{name}" not found')
            if not rv_precondition_ok(f"pods/{key}"):
                return
            # Pod PATCH is scoped to METADATA on this facade. The wire
            # doc is a PARTIAL projection of the truth pod (tolerations,
            # affinity, volumes, limits... are not all serialized), so
            # rebuilding the pod from the merged doc would silently zero
            # every non-wire field on a pure label patch; and a spec
            # patch would bypass the quota/priority admission that
            # guards create. Spec/status mutations therefore answer 422
            # (the Binding subresource owns placement; delete+create is
            # the spec-change path), and the stored pod is built by
            # replacing ONLY metadata on the current truth object.
            cur_doc = pod_to_json(cur)
            merged = merge_patch(cur_doc, patch)
            if (merged.get("spec") != cur_doc.get("spec")
                    or merged.get("status") != cur_doc.get("status")):
                # a textual mismatch can still be semantically identical
                # (kubectl apply re-sends the manifest that CREATED the
                # pod; its "100m"-style quantities differ from the
                # server's canonical rendering): parse both through the
                # same wire projection and compare with metadata
                # normalized — BUT only when the merged doc carries no
                # fields OUTSIDE the projection. The projection ignores
                # unknown fields, so without the foreign-key check a
                # patch adding spec.tolerations or containers[0].image
                # would compare equal and be SILENTLY dropped with a 200
                # (review finding r5).
                try:
                    import dataclasses

                    a = pod_from_json(merged)
                    b = pod_from_json(cur_doc)
                    canon = pod_to_json(a)
                    # Foreign fields split two ways (review r5 round 5):
                    # paths the TRUTH MODEL carries but the wire
                    # projection doesn't (tolerations, affinity,
                    # volumes, limits, ports...) are real data the
                    # facade cannot patch — comparing would silently
                    # drop a semantic change, so they 422. Paths modeled
                    # NOWHERE (containers[].image, env...) are dropped
                    # by the lenient decode exactly as POST drops them —
                    # otherwise re-applying the manifest that CREATED
                    # the pod (kubectl apply's 'unchanged' path) would
                    # fail on fields the create accepted.
                    # (containers[].image is deliberately NOT guarded:
                    # the decode drops it at POST too, so truth never
                    # holds a REST-created pod's image — lenient is the
                    # only symmetric choice; ImageLocality images exist
                    # for in-process pods only)
                    guarded = ("tolerations", "affinity", "volumes",
                               "limits", "ports", "restartPolicy",
                               "topologySpreadConstraints",
                               "priorityClassName")
                    # exact dotted-path SEGMENTS, not substring: an
                    # unmodeled field whose name merely contains a
                    # guarded token ("hostPorts", "volumesAttached")
                    # keeps the documented lenient drop-as-POST-dropped
                    # behavior instead of a spurious 422
                    fk = [
                        p
                        for part in ("spec", "status")
                        for p in foreign_keys(merged.get(part),
                                              canon.get(part))
                        if any(seg in guarded for seg in p.split("."))
                    ]
                    same = (
                        dataclasses.replace(a, labels=b.labels) == b
                        and not fk
                    )
                except Exception:
                    same = False
                if not same:
                    return h._fail(
                        422, "Invalid",
                        "pod PATCH is limited to metadata on this facade "
                        "(placement belongs to the Binding subresource; "
                        "spec changes go through delete+create so "
                        "admission re-runs)")
            meta = merged.get("metadata") or {}
            if meta.get("name") != name:
                return h._fail(422, "Invalid", "metadata.name is immutable")
            if meta.get("namespace", ns) != ns:
                return h._fail(422, "Invalid",
                               "metadata.namespace is immutable")
            if meta.get("uid", cur.uid) != cur.uid:
                return h._fail(422, "Invalid", "metadata.uid is immutable")
            # metadata keys the rebuild below actually carries: labels
            # (mutable) + the server-owned fields echoed back verbatim.
            # Anything else (annotations, finalizers, ownerReferences
            # edits...) would be SILENTLY dropped by the
            # labels-only rebuild — reject it instead (review finding:
            # the spec/status gate never fires on a metadata-only
            # patch, so this was the remaining silent-drop hole).
            # Same split as the spec side: metadata the projection
            # CARRIES (ownerReferences, deletionTimestamp) is
            # server-owned — a patch may only echo it unchanged, else
            # 422 (the labels-only rebuild cannot apply the edit).
            # Metadata modeled nowhere (annotations, finalizers,
            # managedFields — real kubectl apply always writes the
            # last-applied annotation) is dropped as leniently as POST
            # dropped it, keeping apply's 'unchanged' path working.
            cur_meta = cur_doc.get("metadata") or {}
            for k in ("ownerReferences", "deletionTimestamp"):
                if k in meta and meta.get(k) != cur_meta.get(k):
                    return h._fail(
                        422, "Invalid",
                        f"metadata.{k} is server-owned on this facade")
            import dataclasses

            new = dataclasses.replace(
                cur, labels=dict(meta.get("labels") or {}))
            hub.replace_pod(new)
            stored = hub.truth_pods[key]
            return h._respond(200, _with_rv(pod_to_json(stored), hub,
                                            f"pods/{key}"))
        return h._fail(404, "NotFound", h.path)

    # -- DELETE -------------------------------------------------------------

    def _delete(self, h) -> None:
        url_path = urlparse(h.path).path
        routed = self._route_group(url_path)
        if routed is not None:
            group, gseg = routed
            r = self._apps_ns_route(gseg) if group == APPS_GROUP else None
            if r is None or r[1] is None or r[2] is not None:
                return h._fail(404, "NotFound", h.path)
            _, name, _, ns = r
            if ns != "default" or name not in self.hub.deployments:
                return h._fail(404, "NotFound",
                               f'deployments "{name}" not found')
            # cascading: the ownerRef GC pass collects the orphaned RSes
            # and their pods (sim.delete_deployment docstring)
            self.hub.delete_deployment(name)
            return h._respond(200, status_doc(200, "", "")
                              | {"status": "Success"})
        seg = self._route(url_path)
        hub = self.hub
        if not seg:
            return h._fail(404, "NotFound", h.path)
        if len(seg) == 2 and seg[0] == "nodes":
            if seg[1] not in hub.truth_nodes:
                return h._fail(404, "NotFound", f'nodes "{seg[1]}" not found')
            hub.remove_node(seg[1])
            return h._respond(200, status_doc(200, "", "")
                              | {"status": "Success"})
        if len(seg) == 2 and seg[0] == "namespaces":
            # DELETE namespace = start termination; the namespace
            # controller drains and removes it (the reference answers
            # 200 with the Terminating-phase object, registry/core/
            # namespace/storage Delete). Protection lives in the HUB
            # guard so no seam can bypass it.
            ns = hub.namespaces.get(seg[1])
            if ns is None:
                return h._fail(404, "NotFound",
                               f'namespaces "{seg[1]}" not found')
            try:
                hub.terminate_namespace(seg[1])
            except ValueError as e:
                return h._fail(403, "Forbidden", str(e))
            return h._respond(200, ns_to_json(hub, ns))
        if seg[0] == "namespaces" and len(seg) == 4 and seg[2] == "pods":
            key = f"{seg[1]}/{seg[3]}"
            if key not in hub.truth_pods:
                return h._fail(404, "NotFound", f'pods "{seg[3]}" not found')
            hub.delete_pod(key)
            return h._respond(200, status_doc(200, "", "")
                              | {"status": "Success"})
        return h._fail(404, "NotFound", h.path)
