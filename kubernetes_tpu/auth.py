"""Authentication and authorization for the REST facade.

The reference fronts every apiserver request with a filter chain in a
fixed order — authentication, then authorization, then admission
(staging/src/k8s.io/apiserver/pkg/endpoints/filters/authentication.go:41
WithAuthentication, authorization.go:42 WithAuthorization; chain assembly
in pkg/server/config.go:639 DefaultBuildHandlerChain).  This module is
that chain's TPU-framework analog, sized to the hollow control plane:

- :class:`TokenAuthenticator` — the static bearer-token table
  (plugin/pkg/authenticator/token/tokenfile/tokenfile.go:48): maps
  ``Authorization: Bearer <token>`` to a :class:`UserInfo`.  Unknown
  token => 401.  Absent credentials fall through to the anonymous user
  ``system:anonymous`` in group ``system:unauthenticated`` when
  ``anonymous`` is on (pkg/authentication/request/anonymous/anonymous.go:30),
  else 401.
- :class:`RuleAuthorizer` — an RBAC-lite rule list: each
  :class:`Rule` names subjects (users and/or groups) and the
  verbs/resources/namespaces they may touch, "*" wildcards allowed
  (the shape of rbac/v1 PolicyRule, plugin/pkg/auth/authorizer/rbac/rbac.go:79
  RuleAllows).  First matching rule allows; no match => deny
  (RBAC is allow-only, deny is the absence of a grant).
- :class:`AlwaysAllow` / :class:`AlwaysDeny` — the trivial authorizers
  (pkg/auth/authorizer/abac ... authorizerfactory/builtin.go:26).
- :func:`chain` — union of authorizers: first non-NO_OPINION decision
  wins (pkg/authorization/union/union.go:47).

The REST server (restapi.py) runs authenticate -> authorize before any
handler logic, returns Status-shaped 401/403, and stamps the resolved
identity into the audit entry (the reference's audit events carry
``user.username`` the same way — apis/audit/types.go Event.User).
"""

from __future__ import annotations

from typing import Iterable, NamedTuple, Optional, Sequence

ALLOW = "allow"
DENY = "deny"
NO_OPINION = "no-opinion"


class UserInfo(NamedTuple):
    """user.Info (staging/src/k8s.io/apiserver/pkg/authentication/user/user.go:20)."""

    name: str
    groups: tuple = ()
    uid: str = ""


ANONYMOUS = UserInfo(name="system:anonymous",
                     groups=("system:unauthenticated",))


class Unauthenticated(Exception):
    """Raised by an authenticator for a request that presented invalid
    credentials (distinct from presenting none: invalid never falls
    through to anonymous — authentication.go:50 'if err != nil ...401')."""


def _parse_bearer(headers) -> Optional[str]:
    """The ONE bearer-header parse both authenticators share: the token
    string, or None for credential-less/non-Bearer/empty (NO OPINION —
    bearertoken.go:30 returns nil,false,nil; such requests fall through
    to anonymous/fallback policy, they are not failures)."""
    raw = headers.get("Authorization", "") if headers else ""
    parts = raw.split(None, 1)
    if (not raw or len(parts) != 2 or parts[0].lower() != "bearer"
            or not parts[1].strip()):
        return None
    return parts[1].strip()


class TokenAuthenticator:
    """Static token table: ``{token: UserInfo}``.

    ``authenticate(headers)`` returns the matched user, the anonymous
    user (when enabled) for credential-less requests, or raises
    :class:`Unauthenticated` for a malformed/unknown token."""

    def __init__(self, tokens: dict, anonymous: bool = False) -> None:
        for t, u in tokens.items():
            if not isinstance(u, UserInfo):
                raise TypeError(f"token {t!r} must map to UserInfo, got {u!r}")
        self.tokens = dict(tokens)
        self.anonymous = anonymous

    def authenticate(self, headers) -> UserInfo:
        token = _parse_bearer(headers)
        if token is None:
            if self.anonymous:
                return ANONYMOUS
            raise Unauthenticated("no credentials provided")
        user = self.tokens.get(token)
        if user is None:
            # a PRESENT-but-unknown bearer token is a hard failure and
            # never becomes anonymous (bearertoken.go:41 invalid token)
            raise Unauthenticated("invalid bearer token")
        return user


#: the reference's service-account identity shape
#: (serviceaccount/util.go MakeUsername / MakeGroupNames)
SA_USER_PREFIX = "system:serviceaccount:"
SA_GROUP_ALL = "system:serviceaccounts"
SA_GROUP_NS_PREFIX = "system:serviceaccounts:"


def service_account_user(namespace: str, name: str) -> UserInfo:
    """UserInfo for a pod/service-account identity:
    ``system:serviceaccount:<ns>:<name>`` in the all-SAs group and the
    per-namespace group — the exact triple RBAC bindings key on."""
    return UserInfo(
        name=f"{SA_USER_PREFIX}{namespace}:{name}",
        groups=(SA_GROUP_ALL, f"{SA_GROUP_NS_PREFIX}{namespace}"),
    )


class ServiceAccountAuthenticator:
    """Bearer-token authenticator over a LIVE token registry — the
    consumer half of the tokens controller
    (pkg/controller/serviceaccount/tokens_controller.go:73 mints; the
    serviceaccount token authenticator validates). ``lookup`` is a
    callable ``token -> UserInfo | None`` (the hub's revocable registry:
    a deleted namespace revokes its tokens, and this authenticator sees
    that immediately — no static table to go stale).

    Composable: an unknown token consults ``fallback`` (another
    authenticator, e.g. the static TokenAuthenticator for operator
    tokens) before failing; credential-less requests delegate to the
    fallback's anonymous policy, or honor ``anonymous`` here."""

    def __init__(self, lookup, fallback=None, anonymous: bool = False):
        self.lookup = lookup
        self.fallback = fallback
        self.anonymous = anonymous

    def authenticate(self, headers) -> UserInfo:
        token = _parse_bearer(headers)
        if token is None:
            if self.fallback is not None:
                return self.fallback.authenticate(headers)
            if self.anonymous:
                return ANONYMOUS
            raise Unauthenticated("no credentials provided")
        user = self.lookup(token)
        if user is not None:
            return user
        if self.fallback is not None:
            return self.fallback.authenticate(headers)
        raise Unauthenticated("invalid bearer token")


class ServiceAccountNamespaceAuthorizer:
    """RBAC-lite per-namespace binding for EVERY service account: the
    identity minted for namespace X may touch resources ONLY in
    namespace X (the edit-role-per-namespace binding the tokens
    controller implies; a pod-identity token authorizes exactly its
    namespace). Cluster-scoped and non-resource requests are
    NO_OPINION — chain an explicit rule list for those."""

    def __init__(self, verbs: tuple = ("get", "list", "watch", "create",
                                       "update", "patch", "delete")):
        self.verbs = tuple(verbs)

    def authorize(self, a: "Attributes") -> str:
        if not a.resource or not a.namespace:
            return NO_OPINION
        if a.verb not in self.verbs:
            return NO_OPINION
        for g in a.user.groups:
            if (g.startswith(SA_GROUP_NS_PREFIX)
                    and g[len(SA_GROUP_NS_PREFIX):] == a.namespace):
                return ALLOW
        return NO_OPINION


class Attributes(NamedTuple):
    """authorizer.Attributes (authorization/authorizer/interfaces.go:28):
    who is doing what to which resource. A NON-resource request
    (discovery, /openapi/v2, /version — IsResourceRequest false) carries
    ``resource=""`` and the raw ``path`` instead, matched by a Rule's
    ``non_resource_urls`` the way RBAC's NonResourceURLs work."""

    user: UserInfo
    verb: str  # get/list/watch/create/update/delete
    resource: str  # pods/nodes/bindings/...; "" = non-resource request
    namespace: str = ""
    name: str = ""
    path: str = ""  # non-resource URL (set iff resource == "")


class Rule(NamedTuple):
    """One allow-rule. Empty/"*" entries are wildcards. ``subjects``
    match either the username or any group the user carries.
    ``non_resource_urls`` grants NON-resource paths (rbac/v1
    PolicyRule.NonResourceURLs, matched by rbac.go:170
    NonResourceURLMatches): exact paths or a trailing-``*`` prefix like
    ``"/api/*"`` — a rule with them set matches ONLY non-resource
    requests, and resource rules never match non-resource requests
    (``resources=("*",)`` still means every RESOURCE, not discovery)."""

    subjects: tuple  # usernames and/or group names
    verbs: tuple = ("*",)
    resources: tuple = ("*",)
    namespaces: tuple = ("*",)
    non_resource_urls: tuple = ()

    def matches(self, a: Attributes) -> bool:
        subj = set(self.subjects)
        if "*" not in subj and a.user.name not in subj and not (
                subj & set(a.user.groups)):
            return False

        def hit(allowed: tuple, value: str) -> bool:
            return "*" in allowed or value in allowed

        if not hit(self.verbs, a.verb):
            return False
        if not a.resource:  # non-resource request: only URL rules apply
            return any(
                a.path == pat or (pat.endswith("*")
                                  and a.path.startswith(pat[:-1]))
                for pat in self.non_resource_urls
            )
        if self.non_resource_urls:
            return False  # URL rules never grant resource requests
        return (hit(self.resources, a.resource)
                and hit(self.namespaces, a.namespace))


class RuleAuthorizer:
    """Allow iff any rule matches; otherwise NO_OPINION so a chain can
    consult the next authorizer (rbac.go:79 — RBAC never denies, it
    just fails to allow)."""

    def __init__(self, rules: Iterable[Rule]) -> None:
        self.rules = tuple(rules)

    def authorize(self, a: Attributes) -> str:
        return ALLOW if any(r.matches(a) for r in self.rules) else NO_OPINION


class AlwaysAllow:
    def authorize(self, a: Attributes) -> str:
        return ALLOW


class AlwaysDeny:
    def authorize(self, a: Attributes) -> str:
        return DENY


class _Union:
    def __init__(self, members: Sequence) -> None:
        self.members = tuple(members)

    def authorize(self, a: Attributes) -> str:
        for m in self.members:
            d = m.authorize(a)
            if d != NO_OPINION:
                return d
        return NO_OPINION


def chain(*authorizers) -> _Union:
    """Union authorizer: first ALLOW or DENY wins; all-NO_OPINION is a
    deny at the filter (union/union.go:47 + authorization.go:64)."""
    return _Union(authorizers)


def forbidden_message(a: Attributes) -> str:
    """The reference's 403 message shape (responsewriters/errors.go:29):
    'User \"x\" cannot create resource \"pods\" in namespace \"ns\"';
    non-resource requests name the path instead."""
    if not a.resource:
        return f'User "{a.user.name}" cannot {a.verb} path "{a.path}"'
    where = (f' in namespace "{a.namespace}"' if a.namespace
             else " at the cluster scope")
    return (f'User "{a.user.name}" cannot {a.verb} resource '
            f'"{a.resource}"{where}')


# ---------------------------------------------------------------------------
# rbac.authorization.k8s.io role/binding model + aggregation
# ---------------------------------------------------------------------------


class PolicyRule(NamedTuple):
    """rbac/v1 PolicyRule — subject-LESS (who is the binding's job,
    unlike this module's flat :class:`Rule` which couples both; the
    role/binding split is what makes aggregation meaningful)."""

    verbs: tuple = ("*",)
    resources: tuple = ("*",)
    namespaces: tuple = ("*",)
    non_resource_urls: tuple = ()

    def grants(self, a: Attributes) -> bool:
        def hit(allowed: tuple, value: str) -> bool:
            return "*" in allowed or value in allowed

        if not hit(self.verbs, a.verb):
            return False
        if not a.resource:
            return any(
                a.path == pat or (pat.endswith("*")
                                  and a.path.startswith(pat[:-1]))
                for pat in self.non_resource_urls
            )
        if self.non_resource_urls:
            return False
        return (hit(self.resources, a.resource)
                and hit(self.namespaces, a.namespace or "*"))


class ClusterRole:
    """rbac/v1 ClusterRole: named rule set, optionally AGGREGATED — when
    ``aggregation_selectors`` is set, the aggregation controller
    overwrites ``rules`` with the union of every other role matching
    any selector (clusterroleaggregation_controller.go:76
    syncClusterRole; the admin/edit/view stack is built this way)."""

    def __init__(self, name, rules=(), labels=None,
                 aggregation_selectors=()):
        self.name = name
        self.rules = tuple(rules)
        self.labels = dict(labels or {})
        #: each selector is a {label: value} dict (AND of pairs; the
        #: reference's LabelSelectorAsSelector matchLabels form)
        self.aggregation_selectors = tuple(
            dict(s) for s in aggregation_selectors)


class ClusterRoleBinding(NamedTuple):
    """rbac/v1 ClusterRoleBinding: subjects -> one role by name."""

    role: str
    subjects: tuple  # usernames and/or group names


class RBACAuthorizer:
    """The role/binding resolver (rbac.go RBACAuthorizer): a request is
    allowed iff some binding covers the user AND its role (with
    aggregated rules already materialized by the controller) grants the
    attributes. Reads LIVE role/binding dicts — pass the hub's."""

    def __init__(self, roles, bindings) -> None:
        self.roles = roles          # name -> ClusterRole (live dict)
        self.bindings = bindings    # list of ClusterRoleBinding (live)

    def authorize(self, a: Attributes) -> str:
        names = {a.user.name, *a.user.groups}
        for b in self.bindings:
            if "*" not in b.subjects and not (names & set(b.subjects)):
                continue
            role = self.roles.get(b.role)
            if role is not None and any(r.grants(a) for r in role.rules):
                return ALLOW
        return NO_OPINION


def aggregate_cluster_roles(roles) -> int:
    """Aggregation to FIXPOINT (clusterroleaggregation_controller.go:76
    syncClusterRole; the reference converges via re-enqueues on every
    role write — one call here runs passes until nothing changes, so
    CHAINED aggregation like view→edit→admin resolves regardless of
    name order). Each pass: for every role with an aggregation rule,
    rules := union (by-name order, self excluded, deduped preserving
    order) of matching roles' rules. Returns how many role updates
    happened across all passes (0 = already settled). Unions only ever
    grow within a call, so the fixpoint exists even with selector
    cycles; the pass bound is a backstop, not a truncation."""
    total = 0
    for _ in range(max(1, len(roles))):
        changed = 0
        for name in sorted(roles):
            role = roles[name]
            if not role.aggregation_selectors:
                continue
            new_rules = []
            for other_name in sorted(roles):
                if other_name == name:
                    continue
                other = roles[other_name]
                if not any(all(other.labels.get(k) == v
                               for k, v in sel.items())
                           for sel in role.aggregation_selectors):
                    continue
                for r in other.rules:
                    if r not in new_rules:
                        new_rules.append(r)
            if tuple(new_rules) != role.rules:
                role.rules = tuple(new_rules)
                changed += 1
        total += changed
        if not changed:
            break
    return total
