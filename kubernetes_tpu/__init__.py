"""kubernetes_tpu — a TPU-native scheduling framework.

A brand-new implementation of the capabilities of Kubernetes' kube-scheduler
(reference: kubernetes/kubernetes @ ~v1.16), re-designed TPU-first:

- Cluster state is a **columnar snapshot**: dense arrays over nodes and pods
  (the tensor form of the reference's ``NodeInfo``,
  ``pkg/scheduler/nodeinfo/node_info.go:50``).
- Filter predicates are vectorized boolean (pods x nodes) masks; Score
  priorities are vectorized f32 (pods x nodes) matrices. Set-membership
  checks (labels, taints, ports, images) are encoded as multihot matrices so
  they evaluate as matmuls on the MXU.
- Assignment binds the whole pending queue at once: a capacity-aware batched
  solver replaces the reference's one-pod-at-a-time loop
  (``pkg/scheduler/scheduler.go:462`` scheduleOne).
- Scale-out is jax.sharding over a device Mesh: the node axis is sharded,
  score reductions ride ICI collectives — replacing the reference's
  16-goroutine fan-out (``pkg/scheduler/core/generic_scheduler.go:531``) and
  percentageOfNodesToScore subsampling.

Host-side control-plane semantics (scheduling queue with backoff,
assume-then-commit cache, event-driven requeue, preemption with PDBs,
framework extension points) mirror the reference so behavior is checkable
plugin-by-plugin.
"""

__version__ = "0.1.0"


def version_info() -> dict:
    """pkg/version analog (version/base.go Get()): the version document
    every component exposes via --version and /version."""
    import platform as _platform

    return {
        "gitVersion": f"v{__version__}",
        "compatibleReference": "kubernetes v1.16 (scheduler capability set)",
        "platform": f"{_platform.system().lower()}/{_platform.machine()}",
        "pythonVersion": _platform.python_version(),
    }

from kubernetes_tpu.api import types as api_types  # noqa: F401
