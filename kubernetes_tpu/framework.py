"""Scheduling Framework — the v1alpha1 plugin extension points
(``pkg/scheduler/framework/v1alpha1/interface.go``) adapted to the batched
TPU driver.

Extension points and semantics mirror the reference: QueueSort, PreFilter,
Filter, Score, Reserve, Permit, PreBind, Bind, PostBind, Unreserve; Status
codes Success/Error/Unschedulable/Wait/Skip (interface.go:40-53); a
per-cycle CycleState KV store (context.go PluginContext); and a
waiting-pods map for Permit (waiting_pods_map.go).

TPU-first adaptation: the in-tree predicates/priorities are NOT framework
plugins here — they are the fused device kernels (`ops.predicates` /
`ops.priorities`), which is the whole point of the port. The framework
layer is the *extension seam* for everything else, with two plugin flavors:

- **batch plugins** (``filter_batch`` / ``score_batch``): produce a whole
  (P, N) mask/score matrix from the device tables — the idiomatic way to
  add a custom vectorized predicate or priority without leaving the
  device path.
- **host plugins** (``filter`` / ``score``): per-(pod, nodeName) Python
  callbacks matching the reference's signatures — the escape hatch for
  logic that cannot be tensorized (it evaluates once per cycle against
  the packed snapshot and joins the solve as an extra mask/score, which
  keeps the reference's "filter runs before score" contract).

Reserve/Permit/PreBind/Bind/PostBind/Unreserve are host-side by nature
(they guard the assume/bind transaction) and match the reference's
call order in scheduleOne (scheduler.go:462,:531-:598).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import Pod

# ---------------------------------------------------------------------------
# Status (interface.go:40-99)
# ---------------------------------------------------------------------------

SUCCESS = 0
ERROR = 1
UNSCHEDULABLE = 2
WAIT = 3
SKIP = 4

_CODE_NAMES = {SUCCESS: "Success", ERROR: "Error", UNSCHEDULABLE: "Unschedulable",
               WAIT: "Wait", SKIP: "Skip"}


@dataclass
class Status:
    code: int = SUCCESS
    message: str = ""

    def is_success(self) -> bool:
        return self.code == SUCCESS

    def code_name(self) -> str:
        return _CODE_NAMES.get(self.code, str(self.code))


#: the nil-Status convention: None is Success (interface.go:58)
def status_of(s: Optional[Status]) -> Status:
    return s if s is not None else Status()


# ---------------------------------------------------------------------------
# CycleState (context.go PluginContext)
# ---------------------------------------------------------------------------


class CycleState:
    """Per-scheduling-cycle key/value store shared across plugins. The
    reference guards it with a RWMutex for its parallel fan-outs; the host
    driver is single-threaded so plain dict semantics suffice."""

    def __init__(self) -> None:
        self._data: Dict[str, Any] = {}

    def read(self, key: str) -> Any:
        if key not in self._data:
            raise KeyError(key)
        return self._data[key]

    def write(self, key: str, value: Any) -> None:
        self._data[key] = value

    def delete(self, key: str) -> None:
        self._data.pop(key, None)


# ---------------------------------------------------------------------------
# Plugin interfaces. Python duck-typing replaces the Go interface checks:
# a plugin implements an extension point by defining its method.
# ---------------------------------------------------------------------------


class Plugin:
    """Base plugin; subclass and implement any extension-point methods:

    - ``less(pod_info_a, pod_info_b) -> bool``           (QueueSort)
    - ``pre_filter(state, pod) -> Status``               (PreFilter)
    - ``filter(state, pod, node_name) -> Status``        (Filter, host)
    - ``filter_batch(state, dp, dn, ds) -> (P,N) bool``  (Filter, device)
    - ``score(state, pod, node_name) -> (int, Status)``  (Score, host)
    - ``score_batch(state, dp, dn, ds) -> (P,N) f32``    (Score, device)
    - ``score_weight() -> float``                        (Score weight, default 1)
    - ``reserve(state, pod, node_name) -> Status``       (Reserve)
    - ``permit(state, pod, node_name) -> (Status, timeout_s)``  (Permit)
    - ``pre_bind(state, pod, node_name) -> Status``      (PreBind)
    - ``bind(state, pod, node_name) -> Status``          (Bind; SKIP = not handled)
    - ``post_bind(state, pod, node_name)``               (PostBind)
    - ``unreserve(state, pod, node_name)``               (Unreserve)
    """

    def name(self) -> str:
        return type(self).__name__


#: plugin factory registry (framework/v1alpha1/registry.go): name ->
#: factory(args) -> Plugin. Out-of-tree injection point (app/server.go:341
#: WithPlugin analog).
PLUGIN_REGISTRY: Dict[str, Callable[[dict], Plugin]] = {}


def register_plugin(name: str, factory: Callable[[dict], Plugin]) -> None:
    PLUGIN_REGISTRY[name] = factory


# ---------------------------------------------------------------------------
# Waiting pods (Permit -> Wait; waiting_pods_map.go)
# ---------------------------------------------------------------------------


@dataclass
class WaitingPod:
    pod: Pod
    node_name: str
    deadline: float
    allowed: bool = False
    rejected: Optional[str] = None  # rejection message

    def allow(self) -> None:
        self.allowed = True

    def reject(self, msg: str) -> None:
        self.rejected = msg or "rejected"


class WaitingPodsMap:
    def __init__(self) -> None:
        self._pods: Dict[str, WaitingPod] = {}

    def add(self, wp: WaitingPod) -> None:
        self._pods[wp.pod.key()] = wp

    def get(self, key: str) -> Optional[WaitingPod]:
        return self._pods.get(key)

    def remove(self, key: str) -> None:
        self._pods.pop(key, None)

    def items(self) -> List[WaitingPod]:
        return list(self._pods.values())

    def __len__(self) -> int:
        return len(self._pods)


# ---------------------------------------------------------------------------
# Framework (framework.go)
# ---------------------------------------------------------------------------


class Framework:
    """Runs configured plugins at each extension point, in registration
    order, short-circuiting on the first non-success status exactly like
    the reference's Run*Plugins methods (framework.go)."""

    def __init__(
        self,
        plugins: Sequence[Plugin] = (),
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.plugins = list(plugins)
        self.clock = clock
        self.waiting = WaitingPodsMap()

    def _with(self, method: str) -> List[Plugin]:
        return [p for p in self.plugins if hasattr(p, method)]

    # -- queue sort --------------------------------------------------------

    def queue_sort_less(self) -> Optional[Callable]:
        """Only one QueueSort plugin may be enabled (interface.go:131);
        None = use the default priority/timestamp comparator."""
        sorters = self._with("less")
        if len(sorters) > 1:
            raise ValueError("only one QueueSort plugin may be enabled")
        return sorters[0].less if sorters else None

    # -- batched mask/score contributions ----------------------------------

    def has_host_filters(self) -> bool:
        return bool(self._with("filter"))

    def has_host_scores(self) -> bool:
        return bool(self._with("score"))

    def has_batch_filters(self) -> bool:
        return bool(self._with("filter_batch"))

    def has_batch_scores(self) -> bool:
        return bool(self._with("score_batch"))

    def run_prefilter(self, state: CycleState, pod: Pod) -> Status:
        for p in self._with("pre_filter"):
            s = status_of(p.pre_filter(state, pod))
            if not s.is_success():
                return Status(s.code, f"prefilter plugin {p.name()}: {s.message}")
        return Status()

    def run_host_filter(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for p in self._with("filter"):
            s = status_of(p.filter(state, pod, node_name))
            if not s.is_success():
                return s
        return Status()

    def run_host_score(self, state: CycleState, pod: Pod, node_name: str) -> float:
        total = 0.0
        for p in self._with("score"):
            val, s = p.score(state, pod, node_name)
            if not status_of(s).is_success():
                raise RuntimeError(
                    f"score plugin {p.name()} failed: {status_of(s).message}"
                )
            w = p.score_weight() if hasattr(p, "score_weight") else 1.0
            total += w * val
        return total

    def run_filter_batch(self, state: CycleState, dp, dn, ds):
        """AND of all device filter plugins' masks; None when there are
        none (so the solver skips the combine)."""
        mask = None
        for p in self._with("filter_batch"):
            m = p.filter_batch(state, dp, dn, ds)
            mask = m if mask is None else (mask & m)
        return mask

    def run_score_batch(self, state: CycleState, dp, dn, ds):
        total = None
        for p in self._with("score_batch"):
            w = p.score_weight() if hasattr(p, "score_weight") else 1.0
            s = w * p.score_batch(state, dp, dn, ds)
            total = s if total is None else total + s
        return total

    # -- transactional points ---------------------------------------------

    def run_reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for p in self._with("reserve"):
            s = status_of(p.reserve(state, pod, node_name))
            if not s.is_success():
                return Status(ERROR, f"reserve plugin {p.name()}: {s.message}")
        return Status()

    def run_permit(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        """framework.go RunPermitPlugins: any Error/Unschedulable rejects;
        any Wait (with the max timeout) parks the pod in the waiting map —
        the caller must then check ``waiting`` before binding."""
        max_timeout = 0.0
        pending_wait = False
        for p in self._with("permit"):
            s, timeout = p.permit(state, pod, node_name)
            s = status_of(s)
            if s.code in (ERROR, UNSCHEDULABLE):
                return Status(s.code, f"permit plugin {p.name()}: {s.message}")
            if s.code == WAIT:
                pending_wait = True
                max_timeout = max(max_timeout, float(timeout))
        if pending_wait:
            self.waiting.add(
                WaitingPod(pod=pod, node_name=node_name,
                           deadline=self.clock() + max_timeout)
            )
            return Status(WAIT, "waiting on permit")
        return Status()

    def run_prebind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for p in self._with("pre_bind"):
            s = status_of(p.pre_bind(state, pod, node_name))
            if not s.is_success():
                return Status(ERROR, f"prebind plugin {p.name()}: {s.message}")
        return Status()

    def run_bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        """First bind plugin that doesn't Skip handles the pod
        (interface.go:236-241); Skip from all = caller uses the default
        binder."""
        for p in self._with("bind"):
            s = status_of(p.bind(state, pod, node_name))
            if s.code == SKIP:
                continue
            return s
        return Status(SKIP, "no bind plugin handled the pod")

    def run_postbind(self, state: CycleState, pod: Pod, node_name: str) -> None:
        for p in self._with("post_bind"):
            p.post_bind(state, pod, node_name)

    def run_unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        for p in self._with("unreserve"):
            p.unreserve(state, pod, node_name)
