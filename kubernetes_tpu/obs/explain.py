"""Batched schedulability explainer — cluster-wide "why pending"
analytics over the cycle's dense (P, N) predicate-failure bitmask.

kube-scheduler answers "why is this pod pending?" with a truncated
per-pod FitError string assembled in a host loop; the batched design
already materialized the FULL failure picture on device
(:func:`kubernetes_tpu.ops.predicates.run_predicates` records one bit
per failed predicate per (pod, node) pair), so cluster-wide
explainability is one jitted reduction instead of a host sweep:

- **per-pod per-reason node counts** — for each pod, on how many valid
  nodes did each predicate fire (the numbers behind the reference's
  "2 Insufficient cpu, 3 node(s) had taints..." text, but for every
  predicate at once, never truncated);
- **cluster-wide reason histogram** — total (pod, node) failure pairs
  and blocked-pod counts per predicate: which constraint class is
  actually gating the residual queue;
- **one-bit-away relaxation** — for each pod, which SINGLE predicate,
  if relaxed, opens the most nodes: a node is "one bit away" when its
  failure mask is exactly ``1 << b`` (it fails on b and nothing else),
  so relaxing b alone admits it. Cheap exact-one-bit masking on device;
  the provably best single relaxation is the argmax of those counts.

:func:`explain_reduce` is tracer-safe (pure jnp, no host syncs —
graftlint R2/R3 clean, pinned by ``testing.lint_clean`` in tier-1) and
returns small ``(P, B)`` / ``(B,)`` arrays the driver reads back at the
SAME end-of-cycle host boundary where it already syncs the failure
bitmask — the jitted solve path gains zero synchronization points.

Host side, :func:`build_report` decodes those arrays into an
:class:`UnschedulableReport` (per-pod :class:`PodExplanation` rows plus
the cluster rollup) that feeds the ``/debug/why`` endpoint, the flight
recorder's top-K reasons, the ``scheduler_unschedulable_*`` metrics,
and ``kubectl describe pod``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops.predicates import PREDICATE_BITS, REASON_MESSAGES

#: number of predicate reason bits (the static axis of every reduction)
N_REASONS = len(PREDICATE_BITS)


class ExplainResult(NamedTuple):
    """Device outputs of :func:`explain_reduce` (everything int32)."""

    #: (P, B) — valid nodes on which predicate b fired for pod p
    per_pod: jnp.ndarray
    #: (P, B) — valid nodes failing ONLY on predicate b (one bit away)
    one_bit: jnp.ndarray
    #: (P,) — argmax_b one_bit: the best single relaxation per pod
    best_bit: jnp.ndarray
    #: (P,) — nodes that best relaxation would open
    best_gain: jnp.ndarray
    #: (P,) — valid nodes with NO failure bits (the pod lost a capacity
    #: race to the rest of the batch rather than failing predicates)
    feasible: jnp.ndarray
    #: (B,) — total (pod, node) failure pairs per predicate
    pair_hist: jnp.ndarray
    #: (B,) — pods with predicate b firing on >= 1 valid node
    pods_blocked: jnp.ndarray
    #: (P,) — OR of every valid node's failure bits per pod (what the
    #: driver used to read the whole (P, N) matrix back to compute)
    pod_bits: jnp.ndarray
    #: (P, R) — valid nodes where PodFitsResources fired AND the pod's
    #: request for resource r exceeds the node's free amount — the
    #: per-resource "Insufficient <res>" counts of FitError.Error()
    #: ((P, 0) when the fit inputs weren't supplied)
    insufficient: jnp.ndarray
    #: (P,) — valid nodes where CheckNodeCondition fired and the node was
    #: not ready (zeros when fit inputs weren't supplied)
    not_ready: jnp.ndarray
    #: (P,) — ...and where the node's network was unavailable
    net_unavail: jnp.ndarray


@jax.jit
def explain_reduce(reasons, node_valid, pod_mask, req=None, free=None,
                   ready=None, net_unavail=None) -> ExplainResult:
    """Reduce the cycle's failure bitmask into the explain analytics.

    ``reasons`` (P, N) int32 per-(pod, node) failed-predicate bits (from
    :class:`~kubernetes_tpu.ops.predicates.FilterResult`); ``node_valid``
    (N,) bool; ``pod_mask`` (P,) bool selects the pods under analysis
    (the cycle's unschedulable rows — placed and padded rows contribute
    nothing to the cluster rollup).

    ``req`` (P, R) / ``free`` (N, R) / ``ready`` / ``net_unavail`` (N,)
    are the FitError fidelity inputs: with them the result additionally
    carries the per-resource Insufficient counts and the node-condition
    splits, so the driver reconstructs ``fit_error_message`` output
    byte-identically from these reductions (see
    :func:`~kubernetes_tpu.ops.predicates.fit_error_message_from_counts`)
    — the raw (P, N) bitmask never crosses the device boundary.

    The reason axis is static (``N_REASONS`` bits), so it unrolls as B
    passes over the (P, N) plane — the same streaming idiom as
    :func:`~kubernetes_tpu.ops.predicates.resource_fit_mask`; no
    (P, N, B) intermediate is ever materialized.
    """
    from kubernetes_tpu.ops.predicates import BIT

    vmask = pod_mask[:, None] & node_valid[None, :]  # (P, N)
    P = reasons.shape[0]
    per_pod_cols = []
    one_bit_cols = []
    # OR over valid nodes, assembled bit-by-bit from boolean
    # any-reductions: sum_b (1 << b) * any(bit b fired) is exactly the
    # bitwise OR (each term owns its bit). The direct int32
    # lax.bitwise_or reduce this replaces is NOT a collective XLA:CPU
    # can lower when the node axis is mesh-sharded (s32 `or`
    # all-reduce: "Unsupported reduction computation"); boolean any()
    # is — and the per-bit `fired` planes are computed for the counts
    # below anyway. Independent of pod_mask so the value matches the
    # legacy host reduction for every failed row.
    pod_bits = jnp.zeros((P,), jnp.int32)
    for b in range(N_REASONS):
        fired = ((reasons >> b) & 1) > 0
        per_pod_cols.append(
            jnp.sum(fired & vmask, axis=1, dtype=jnp.int32))
        only = (reasons == jnp.int32(1 << b)) & vmask
        one_bit_cols.append(jnp.sum(only, axis=1, dtype=jnp.int32))
        pod_bits = pod_bits + (
            jnp.int32(1 << b)
            * jnp.any(fired & node_valid[None, :], axis=1
                      ).astype(jnp.int32))
    per_pod = jnp.stack(per_pod_cols, axis=1)  # (P, B)
    one_bit = jnp.stack(one_bit_cols, axis=1)  # (P, B)
    best_bit = jnp.argmax(one_bit, axis=1).astype(jnp.int32)
    best_gain = jnp.max(one_bit, axis=1)
    feasible = jnp.sum((reasons == 0) & vmask, axis=1, dtype=jnp.int32)
    pair_hist = jnp.sum(per_pod, axis=0, dtype=jnp.int32)
    pods_blocked = jnp.sum(per_pod > 0, axis=0, dtype=jnp.int32)
    if req is not None:
        res_fired = (((reasons >> BIT["PodFitsResources"]) & 1) > 0) \
            & node_valid[None, :]
        insufficient = jnp.stack([
            jnp.sum(res_fired
                    & (req[:, r:r + 1] > free[None, :, r] + 1e-6),
                    axis=1, dtype=jnp.int32)
            for r in range(req.shape[1])
        ], axis=1)  # (P, R)
        cond_fired = (((reasons >> BIT["CheckNodeCondition"]) & 1) > 0) \
            & node_valid[None, :]
        not_ready = jnp.sum(cond_fired & ~ready[None, :], axis=1,
                            dtype=jnp.int32)
        netun = jnp.sum(cond_fired & net_unavail[None, :], axis=1,
                        dtype=jnp.int32)
    else:
        insufficient = jnp.zeros((P, 0), jnp.int32)
        not_ready = jnp.zeros((P,), jnp.int32)
        netun = jnp.zeros((P,), jnp.int32)
    return ExplainResult(per_pod, one_bit, best_bit, best_gain,
                         feasible, pair_hist, pods_blocked,
                         pod_bits, insufficient, not_ready, netun)


# ---------------------------------------------------------------------------
# host-side report (decoded once per cycle at the existing host boundary)
# ---------------------------------------------------------------------------


@dataclass
class PodExplanation:
    """Why ONE pod stayed pending this cycle."""

    key: str = ""
    #: predicate name -> number of valid nodes it excluded
    reason_node_counts: Dict[str, int] = field(default_factory=dict)
    #: (predicate name, nodes a solo relaxation would open), best first
    relaxations: List[Tuple[str, int]] = field(default_factory=list)
    #: valid nodes with no failure bits — the pod was feasible somewhere
    #: but lost the in-batch capacity race (or an extender/plugin said no)
    feasible_nodes: int = 0
    #: scheduling attempts so far (backoff-map count incl. this cycle)
    attempts: int = 0
    #: seconds since the pod first entered the queue
    queue_residency_s: float = 0.0
    #: the driver's failure-reason tuple (plugin/gang/extender failures
    #: carry their status here even without predicate bits)
    reasons: Tuple[str, ...] = ()
    #: FitError-shaped message when the failure came from the filter pass
    message: str = ""

    def to_json(self) -> dict:
        return {
            "pod": self.key,
            "reason_node_counts": dict(self.reason_node_counts),
            "relaxations": [
                {"reason": r, "nodes_opened": n} for r, n in self.relaxations
            ],
            "feasible_nodes": self.feasible_nodes,
            "attempts": self.attempts,
            "queue_residency_s": round(self.queue_residency_s, 3),
            "reasons": list(self.reasons),
            "message": self.message,
        }


@dataclass
class UnschedulableReport:
    """One cycle's cluster-wide unschedulability rollup."""

    cycle: int = 0
    n_nodes: int = 0
    pods: Dict[str, PodExplanation] = field(default_factory=dict)
    #: predicate name -> total (pod, node) failure pairs
    reason_node_counts: Dict[str, int] = field(default_factory=dict)
    #: predicate name -> pods blocked by it on >= 1 node
    reason_pods: Dict[str, int] = field(default_factory=dict)

    def top_reasons(self, k: int = 3) -> List[Tuple[str, int]]:
        """Top-K predicates by blocked-pod count (flight-recorder row)."""
        return sorted(
            self.reason_pods.items(), key=lambda kv: (-kv[1], kv[0])
        )[:k]

    def to_json(self) -> dict:
        return {
            "cycle": self.cycle,
            "nodes": self.n_nodes,
            "unschedulable": len(self.pods),
            "reason_node_counts": dict(self.reason_node_counts),
            "reason_pods": dict(self.reason_pods),
            "pods": sorted(self.pods),
        }


def build_report(
    cycle: int,
    n_nodes: int,
    pod_keys: List[str],
    rows: Iterable[int],
    ex: Optional[dict] = None,
    top_k: int = 3,
) -> UnschedulableReport:
    """Decode read-back :func:`explain_reduce` arrays into the report.

    ``pod_keys`` is the cycle batch in row order; ``rows`` holds the
    batch indices of the unschedulable pods under analysis (the explain
    arrays are full-batch-indexed, so the same index addresses both);
    ``ex`` holds the HOST (numpy) arrays keyed like
    :class:`ExplainResult` (None when the explain pass was gated off —
    the report then carries only driver-level reasons filled in by the
    caller).
    """
    rep = UnschedulableReport(cycle=cycle, n_nodes=n_nodes)
    for i in rows:
        key = pod_keys[i]
        pe = PodExplanation(key=key)
        if ex is not None:
            counts = ex["per_pod"][i]
            pe.reason_node_counts = {
                PREDICATE_BITS[b]: int(counts[b])
                for b in range(N_REASONS) if counts[b]
            }
            one = ex["one_bit"][i]
            order = sorted(
                (b for b in range(N_REASONS) if one[b]),
                key=lambda b: (-int(one[b]), b),
            )
            pe.relaxations = [
                (PREDICATE_BITS[b], int(one[b])) for b in order[:top_k]
            ]
            pe.feasible_nodes = int(ex["feasible"][i])
        rep.pods[key] = pe
    if ex is not None:
        rep.reason_node_counts = {
            PREDICATE_BITS[b]: int(ex["pair_hist"][b])
            for b in range(N_REASONS) if ex["pair_hist"][b]
        }
        rep.reason_pods = {
            PREDICATE_BITS[b]: int(ex["pods_blocked"][b])
            for b in range(N_REASONS) if ex["pods_blocked"][b]
        }
    return rep


def reason_message(name: str) -> str:
    """Human text for a predicate name (FitError vocabulary where one
    exists; the registration name otherwise)."""
    return REASON_MESSAGES.get(name, name)


def summarize_breakdown(reason_pods: Dict[str, int], n_nodes: int) -> str:
    """The ``0/N nodes are available: ...`` line for a cluster rollup —
    counts here are BLOCKED PODS per reason (the cluster view), sorted
    like sortReasonsHistogram sorts the per-pod node counts."""
    parts = sorted(
        f"{v} x {reason_message(k)}" for k, v in reason_pods.items())
    return (f"0/{n_nodes} nodes available for the residual queue: "
            + ", ".join(parts)) if parts else "no unschedulable pods"
