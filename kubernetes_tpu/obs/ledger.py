"""Perf ledger — per-cycle cost-model accounting and the online SLO
watchdog (the falsification instrument ROADMAP item 1 asks for).

Until this module, ``model_efficiency ≥ 0.9999`` in the committed mesh
records was a claim about a MODEL (parallel/costmodel.py) that nothing
at runtime ever confronted with what cycles actually cost, and the
serving loop had p99 *targets* but no online watchdog noticing when
they erode. The ledger closes both gaps, kube-scheduler-style: like
``scheduler_perf``, everything is ultimately judged by measured latency
distributions — the model exists to be compared against them, never to
replace them.

Three pieces, one :class:`PerfLedger` facade the scheduler's
:class:`~kubernetes_tpu.obs.core.Observability` owns:

- **Measured side** — every eventful cycle's flight record
  (``CycleRecord.spans`` — the spans the driver already emits:
  snapshot / pack / dispatch / solve:{tier} / validate / readback /
  bind, pipeline chunks, restricted-vs-cold ``solve_scope``) is grouped
  into canonical PHASES and folded into rolling per-phase ×
  per-solve-scope × per-mesh-size distributions (p50/p99 over a bounded
  sample window, plus an EWMA). The ledger consumes ``end_cycle``
  output on the host; it adds **zero** device syncs and never touches
  jitted code.
- **Modeled side** (:class:`CycleCostModel`) — at warmup the scheduler
  captures XLA ``cost_analysis()`` (flops / bytes-accessed) per
  compiled solve signature plus one *timed warm replay* as the rate
  anchor; live cycles without a warmup self-anchor on their first
  measured solve. A cycle's predicted solve cost scales the anchor by
  the analytic work ratio (captured flops when available, else the
  dense ``P·N`` plane; restricted solves scale with ``P`` alone — the
  candidate bucket is a fixed static shape) divided across the mesh and
  discounted by :func:`parallel.costmodel.model_efficiency` — the SAME
  function the weak-scaling bench reports, so bench and runtime cannot
  disagree. ``modeled/measured`` lands on the CycleResult, the flight
  record (``eff=0.87`` flag), ``scheduler_cycle_model_efficiency``, and
  a Chrome-trace counter track so Perfetto shows efficiency alongside
  the spans.
- **SLO watchdog** (:class:`SLOWatchdog`) — multi-window burn-rate
  evaluation (Google-SRE style: page only when the FAST and the SLOW
  window both burn) over two configurable objectives: create-to-bind
  p99 (``e2e_p99_objective_s``; error budget: 1% of pods may exceed
  the target) and cycle-cost drift vs a rolling EWMA baseline
  (``cost_drift_ratio``; budget: 10% of cycles may exceed
  ratio × baseline). Transitions emit ``SchedulerSLOBurn`` /
  ``SchedulerSLORecovered`` events through events.py (the recorder's
  spam filter aggregates recurrences), export
  ``scheduler_slo_burn_rate{objective,window}``, and — while burning —
  engage :meth:`Scheduler.is_degraded` so APF admission sheds EARLIER
  at the same queue depth (``engage_pressure``).

Everything runs on the owner's injected clock (deterministic under
fake clocks, graftlint R4-clean) and is thread-safe: the scheduler
thread observes while the ``/debug/ledger`` handler thread snapshots.
"""

from __future__ import annotations

import math
import re
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.sanitize import make_lock

#: span name -> canonical phase. Pipeline spans carry their chunk index
#: (``pipeline:pack@3``) — the phase is the stage name; ladder spans
#: carry their tier (``solve:batch``) — the phase is "solve" so the
#: restricted/cold split rides the SCOPE axis, not the phase axis.
_PHASE_NAMES = ("snapshot", "validate", "bind", "preemption")

#: objectives' error budgets: a p99 target tolerates 1% of samples over
#: it by definition; the drift objective tolerates 10% of cycles over
#: ratio x baseline before burn = 1 (transient spikes are not incidents)
E2E_ERROR_BUDGET = 0.01
DRIFT_ERROR_BUDGET = 0.10

#: min clock seconds between pressure-probe window re-evaluations
#: (PerfLedger.pressure_engaged) — bounds burn-recovery staleness as
#: seen by request threads without an evaluate per mutating call
PRESSURE_EVAL_INTERVAL_S = 1.0

_SHAPE_RE = re.compile(r"^P(\d+)xN(\d+)")


def phase_of(span_name: str) -> str:
    """Canonical phase of one span name ('' = not a phase: the cycle
    root)."""
    if span_name.startswith("pipeline:"):
        # pipeline:pack@3 -> pack; pipeline:readback@reasons -> readback
        return span_name.split(":", 1)[1].split("@", 1)[0]
    if span_name.startswith("solve:"):
        return "solve"
    if span_name.startswith(("extender", "grpc")):
        return "extenders"
    if span_name.startswith("scenario"):
        return "scenario"
    if span_name in _PHASE_NAMES:
        return span_name
    if span_name == "Scheduling cycle":
        return ""  # the root frame is the total, not a phase
    return "other"


def parse_batch_shape(digest: str) -> Tuple[int, int]:
    """(padded P, padded N) from the flight record's batch-shape digest
    (``P4096xN65536+topo+mesh8``); (0, 0) when the cycle never packed."""
    m = _SHAPE_RE.match(digest or "")
    return (int(m.group(1)), int(m.group(2))) if m else (0, 0)


def _quantile(sorted_vals, q: float) -> float:
    """Nearest-rank quantile over an already-sorted sequence — THE one
    implementation both the rolling distributions (/debug/ledger) and
    the bench arm summaries use, so the percentiles the ``ledger``
    gate enforces can never diverge from the live ones."""
    n = len(sorted_vals)
    return sorted_vals[min(n - 1, max(0, math.ceil(q * n) - 1))]


class RollingDist:
    """Bounded sample window + EWMA for one (phase, scope, mesh) cell.
    p50/p99 come from the retained window (newest ``window`` samples);
    the EWMA is the cheap always-on trend the drift baseline rides."""

    __slots__ = ("samples", "ewma", "n", "alpha")

    def __init__(self, window: int = 256, alpha: float = 0.05) -> None:
        self.samples: deque = deque(maxlen=max(1, int(window)))
        self.ewma = 0.0
        self.n = 0
        self.alpha = min(max(float(alpha), 1e-6), 1.0)

    def observe(self, v: float) -> None:
        v = float(v)
        self.samples.append(v)
        self.ewma = v if self.n == 0 else (
            self.alpha * v + (1.0 - self.alpha) * self.ewma)
        self.n += 1

    def quantile(self, q: float) -> float:
        if not self.samples:
            return 0.0
        return _quantile(sorted(self.samples), q)

    def to_json(self) -> dict:
        return {"n": self.n, "p50_s": round(self.quantile(0.5), 6),
                "p99_s": round(self.quantile(0.99), 6),
                "ewma_s": round(self.ewma, 6)}


@dataclass
class LedgerEntry:
    """One cycle's ledger row: the measured phase costs, the model's
    prediction for the same shape, and the gap."""

    cycle: int = 0
    t: float = 0.0
    batch_shape: str = ""
    scope: str = ""          # restricted | full | "" (no solve)
    mesh: int = 0            # devices the cycle ran on (0 = single)
    phases: Dict[str, float] = field(default_factory=dict)
    measured_s: float = 0.0  # cycle wall (CycleRecord.elapsed_s)
    solve_s: float = 0.0     # measured solve(+dispatch) phase total
    modeled_s: float = -1.0  # predicted solve cost (-1 = no prediction)
    efficiency: float = -1.0  # modeled/measured solve (-1 = unpopulated)
    model_basis: str = ""    # xla-cost | calibrated | anchor | ""
    slo: str = ""            # comma-joined burning objectives ("" = ok)

    def to_json(self) -> dict:
        return {
            "cycle": self.cycle,
            "t": round(self.t, 6),
            "batch_shape": self.batch_shape,
            "scope": self.scope,
            "mesh": self.mesh,
            "phases": {k: round(v, 6) for k, v in sorted(
                self.phases.items())},
            "measured_s": round(self.measured_s, 6),
            "solve_s": round(self.solve_s, 6),
            **({"modeled_s": round(self.modeled_s, 6),
                "model_efficiency": round(self.efficiency, 4),
                "model_basis": self.model_basis}
               if self.efficiency >= 0 else {}),
            **({"slo": self.slo} if self.slo else {}),
        }


class CycleCostModel:
    """The modeled side: per-signature XLA cost capture + rate anchors.

    ``record_signature`` lands warmup's ``cost_analysis()`` capture
    (flops / bytes-accessed per compiled (P, N) solve shape);
    ``record_anchor`` offers a measured warm solve (warmup's timed
    replay, and every live cycle) — the best seconds-per-work rate
    wins, so a compile-swallowing cold cycle never becomes the
    reference. ``predict`` scales the anchor by the analytic work ratio — captured
    flops when BOTH shapes carry one (basis ``xla-cost``), else the
    dense ``P·N`` plane (restricted solves: ``P`` — the candidate
    bucket is one static shape) — normalized to single-device work via
    ``/devices/model_efficiency(...)`` so one anchor predicts every
    mesh width with parallel/costmodel.py's collective model folded
    in."""

    def __init__(self, lock_factory=None) -> None:
        self._lock = make_lock(lock_factory, "obs.costmodel")
        #: (P, N) -> {"flops": float, "bytes_accessed": float}
        self._sig: Dict[Tuple[int, int], Dict[str, float]] = {}
        #: scope -> (P, N, mesh, solve_s, rounds) — the BEST observed
        #: rate wins (lowest seconds per work unit): the anchor is the
        #: speed-of-light reference, so a cold cycle whose solve span
        #: swallowed an XLA compile can never become the baseline, and
        #: drift reads as efficiency < 1 against the best the hardware
        #: has demonstrably done (never a silent re-base upward)
        self._anchor: Dict[str, Tuple[int, int, int, float, int]] = {}

    def record_signature(self, P: int, N: int, flops: float,
                         bytes_accessed: float = 0.0) -> None:
        if flops and flops > 0:
            with self._lock:
                self._sig[(int(P), int(N))] = {
                    "flops": float(flops),
                    "bytes_accessed": float(bytes_accessed or 0.0)}

    def record_anchor(self, scope: str, P: int, N: int, mesh: int,
                      solve_s: float, rounds: int = 1) -> bool:
        """Offer a measured solve as the scope's rate anchor; installs
        it only when its seconds-per-work-unit beat the current anchor
        (or none exists). Returns True when installed."""
        if solve_s <= 0 or P <= 0:
            return False
        scope = scope or "full"
        work = self._work(P, N, mesh, scope, None, rounds)
        if work <= 0:
            return False
        rate = float(solve_s) / work
        with self._lock:
            cur = self._anchor.get(scope)
            if cur is not None:
                cP, cN, cMesh, cS, cR = cur
                cur_work = self._work(cP, cN, cMesh, scope, None, cR)
                if cur_work > 0 and rate >= cS / cur_work:
                    return False
            self._anchor[scope] = (int(P), int(N), int(mesh),
                                   float(solve_s), max(int(rounds), 1))
            return True

    def _work(self, P: int, N: int, mesh: int, scope: str,
              flops: Optional[float], rounds: int) -> float:
        """Single-device-equivalent work units for one solve: the
        per-round plane cost (captured ``flops``, read out of ``_sig``
        under the caller's lock — this helper runs locked AND unlocked,
        so it must not touch shared state itself — or the analytic P·N)
        times the round count, divided across the mesh and discounted
        by the collective model."""
        from kubernetes_tpu.parallel.costmodel import model_efficiency

        if flops is not None:
            base = flops
        elif scope == "restricted":
            # the restricted solve gathers a FIXED candidate bucket:
            # cost scales with the batch, not the node axis
            base = float(max(P, 1))
        else:
            base = float(max(P, 1)) * float(max(N, 1))
        d = max(int(mesh), 1)
        return (base * max(int(rounds), 1)
                / d / model_efficiency(d, P, max(N, 1)))

    def predict(self, P: int, N: int, mesh: int, scope: str,
                rounds: int = 1) -> Tuple[Optional[float], str]:
        """(modeled solve seconds, basis) — (None, "") when no anchor
        exists yet for this scope (the caller self-anchors). No
        cross-scope fallback: restricted work units (P) and full work
        units (P·N) are incommensurable, so scaling a full anchor for a
        restricted query would produce a confidently wrong verdict."""
        scope = scope or "full"
        with self._lock:
            anchor = self._anchor.get(scope)
            if anchor is None:
                return None, ""
            aP, aN, aMesh, aS, aRounds = anchor
            use_flops = (scope != "restricted"
                         and (P, N) in self._sig
                         and (aP, aN) in self._sig)
            # snapshot the flops while still under the lock: _work runs
            # unlocked and a concurrent record_signature replaces entries
            q_flops = self._sig[(P, N)]["flops"] if use_flops else None
            a_flops = self._sig[(aP, aN)]["flops"] if use_flops else None
        work = self._work(P, N, mesh, scope, q_flops, rounds)
        anchor_work = self._work(aP, aN, aMesh, scope, a_flops, aRounds)
        if anchor_work <= 0:
            return None, ""
        basis = "xla-cost" if use_flops else "calibrated"
        return aS * work / anchor_work, basis

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "signatures": {
                    f"P{p}xN{n}": dict(v)
                    for (p, n), v in sorted(self._sig.items())},
                "anchors": {
                    scope: {"P": a[0], "N": a[1], "mesh": a[2],
                            "solve_s": round(a[3], 6), "rounds": a[4]}
                    for scope, a in sorted(self._anchor.items())},
            }


def capture_cost_analysis(lower_fn: Callable[[], object]) -> Optional[dict]:
    """Best-effort XLA cost capture: ``lower_fn`` returns a lowered
    jitted computation; its ``cost_analysis()`` flops / bytes-accessed
    come back, or None when the backend (or the signature) declines
    AOT analysis — capture failure must never fail warmup.

    Tries the LOWERED stage first (no backend compile); only when that
    yields nothing does it pay ``compile()`` — the AOT compile does not
    share the jit call cache, so falling through costs one extra
    (smallest-bucket) compilation at warmup."""

    def _usable(ca) -> Optional[dict]:
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if not isinstance(ca, dict):
            return None
        flops = float(ca.get("flops", 0.0) or 0.0)
        if flops <= 0:
            return None
        return {"flops": flops,
                "bytes_accessed": float(ca.get("bytes accessed", 0.0)
                                        or 0.0)}

    try:
        lowered = lower_fn()
    except Exception:
        return None
    try:
        out = _usable(lowered.cost_analysis())
        if out is not None:
            return out
    except Exception:
        pass
    try:
        return _usable(lowered.compile().cost_analysis())
    except Exception:
        return None


class _BurnWindow:
    """One objective × one window: a sample deque with rolling
    bad/total sums so the burn rate is O(1) per read.
    ``pressure_engaged`` probes the watchdog from request threads on
    every mutating call while burning — re-scanning a slow-window-sized
    deque per request would cost the most exactly when the system is
    already degraded."""

    __slots__ = ("window_s", "dq", "bad", "total")

    def __init__(self, window_s: float) -> None:
        self.window_s = float(window_s)
        self.dq: deque = deque()
        self.bad = 0
        self.total = 0

    def add(self, t: float, bad: int, total: int) -> None:
        self.dq.append((t, bad, total))
        self.bad += bad
        self.total += total
        self.prune(t)

    def prune(self, now: float) -> None:
        lo = now - self.window_s
        dq = self.dq
        while dq and dq[0][0] < lo:
            _, b, n = dq.popleft()
            self.bad -= b
            self.total -= n

    def rate(self, budget: float, now: float) -> float:
        self.prune(now)
        if self.total <= 0:
            return 0.0
        return (self.bad / self.total) / max(budget, 1e-9)


class SLOWatchdog:
    """Multi-window burn-rate evaluation over the ledger's objectives.

    Per objective: a (fast, slow) ``_BurnWindow`` pair with rolling
    sums; ``burn(window) = violating_fraction / error_budget``.
    The state machine trips to *burning* when BOTH windows' burn rates
    reach ``burn_threshold`` (fast alone is a blip, slow alone is old
    news — the SRE multi-window rule) and recovers when the FAST window
    drops back under. An EMPTY fast window reads burn rate 0 and so
    RECOVERS a standing burn — the SRE no-traffic convention (no
    samples = no error budget spent), chosen deliberately: holding a
    burn on silence would let one permanently-unschedulable pod pin
    degraded shedding forever, and during a true total stall the APF
    probe still sheds on raw queue depth (``backend_pressure``'s base
    term) even after the degraded multiplier drops. Transitions emit
    events through the installed sink and count in ``burns`` so the
    benches can assert clean arms stayed at zero.

    Trips require FRESH evidence: only an evaluation riding an
    ``_observe`` (a cycle just folded samples in) may flip an
    objective to burning; the clock-driven re-evaluations (idle tick,
    pressure probe) pass ``allow_trip=False`` and may only recover.
    Without this, a quiet period after a loud one can page on stale
    samples: as the fast window drains oldest-first, the violating
    FRACTION of what remains can rise and cross the threshold with no
    new traffic at all (the soak's clean window after the
    network-fault phase caught exactly this flap)."""

    def __init__(self, config, clock: Callable[[], float] = time.monotonic,
                 metrics=None, lock_factory=None) -> None:
        self.config = config
        self.clock = clock
        self.metrics = metrics
        #: event sink: (reason, involved ObjectRef, message) -> None;
        #: the Scheduler wires its own event_sink here
        self.event_sink: Optional[Callable] = None
        #: guards every state dict below: the scheduler thread observes
        #: while /debug/ledger snapshots AND request threads re-evaluate
        #: through pressure_engaged — an unlocked dict iteration there
        #: can raise "dictionary changed size during iteration"
        self._lock = make_lock(lock_factory, "obs.watchdog")
        #: objective name -> (fast, slow) _BurnWindow pair
        self._samples: Dict[str, Tuple[_BurnWindow, _BurnWindow]] = {}
        #: objective name -> burning?
        self._burning: Dict[str, bool] = {}
        #: burn transitions per objective (monotone; bench gate input)
        self.burns: Dict[str, int] = {}
        #: rolling cost baseline per solve scope (EWMA seconds)
        self._baseline: Dict[str, float] = {}

    # -- objectives ---------------------------------------------------------

    def objectives(self) -> List[Tuple[str, float]]:
        out = []
        if self.config.e2e_p99_objective_s > 0:
            out.append(("e2e_p99", E2E_ERROR_BUDGET))
        if self.config.cost_drift_ratio > 0:
            out.append(("cost_drift", DRIFT_ERROR_BUDGET))
        return out

    def _observe(self, objective: str, t: float, bad: int,
                 total: int) -> None:
        # caller holds self._lock
        wins = self._samples.get(objective)
        if wins is None:
            wins = self._samples[objective] = (
                _BurnWindow(self.config.fast_window_s),
                _BurnWindow(self.config.slow_window_s))
        for w in wins:
            w.add(t, int(bad), int(total))

    def burn_rate(self, objective: str, window_s: float,
                  budget: float, now: float) -> float:
        # caller holds self._lock (the windows must not grow mid-read)
        wins = self._samples.get(objective)
        if wins is None:
            return 0.0
        for w in wins:
            if w.window_s == window_s:
                return w.rate(budget, now)
        # only the configured fast/slow windows are maintained
        return 0.0

    def observe_cycle(self, t: float, e2e_latencies, solve_s: float,
                      scope: str) -> str:
        """Fold one cycle's evidence in, run the state machine, return
        the comma-joined burning-objective string for the records."""
        observed = False
        with self._lock:
            if self.config.e2e_p99_objective_s > 0 and e2e_latencies:
                target = self.config.e2e_p99_objective_s
                bad = sum(1 for v in e2e_latencies if v > target)
                self._observe("e2e_p99", t, bad, len(e2e_latencies))
                observed = True
            if self.config.cost_drift_ratio > 0 and solve_s > 0:
                scope = scope or "full"
                base = self._baseline.get(scope)
                violated = False
                if base is not None and base > 0:
                    violated = solve_s > self.config.cost_drift_ratio * base
                    self._observe("cost_drift", t, int(violated), 1)
                    observed = True
                a = min(max(self.config.baseline_decay, 1e-6), 1.0)
                if violated:
                    # slow the re-base 10x while violating: a step
                    # regression must fill the burn windows and TRIP
                    # before the baseline absorbs it (at full decay the
                    # violation count is bounded by ~ln(r/(r-1))/decay
                    # regardless of magnitude — the silent upward
                    # re-base this watchdog exists to prevent). A
                    # persistent new normal still re-bases eventually,
                    # so the burn recovers instead of pinning degraded.
                    a *= 0.1
                self._baseline[scope] = (solve_s if base is None
                                         else a * solve_s + (1 - a) * base)
        # an eventful cycle that folded NOTHING in (no latencies, no
        # solve) is clock, not evidence — recovery-only, like the ticks
        return self.evaluate(t, allow_trip=observed)

    def evaluate(self, now: float, allow_trip: bool = True) -> str:
        """Run the state machine over both windows. Safe from ANY
        thread (locked); events emit after the lock drops so a sink
        calling back into the ledger cannot deadlock.
        ``allow_trip=False`` (the clock-driven callers) restricts the
        machine to recovery — a burn never STARTS on window expiry."""
        burning: List[str] = []
        emissions: List[Tuple[str, str, str]] = []
        gauges: List[Tuple[float, str, str]] = []
        with self._lock:
            for objective, budget in self.objectives():
                fast = self.burn_rate(objective,
                                      self.config.fast_window_s,
                                      budget, now)
                slow = self.burn_rate(objective,
                                      self.config.slow_window_s,
                                      budget, now)
                gauges.append((round(fast, 4), objective, "fast"))
                gauges.append((round(slow, 4), objective, "slow"))
                was = self._burning.get(objective, False)
                thr = self.config.burn_threshold
                if not was and allow_trip and fast >= thr and slow >= thr:
                    self._burning[objective] = True
                    self.burns[objective] = (
                        self.burns.get(objective, 0) + 1)
                    emissions.append((
                        "SchedulerSLOBurn", objective,
                        f"SLO {objective} burning: fast-window burn "
                        f"rate {fast:.1f}, slow {slow:.1f} "
                        f"(threshold {thr:g})"))
                elif was and fast < thr:
                    self._burning[objective] = False
                    emissions.append((
                        "SchedulerSLORecovered", objective,
                        f"SLO {objective} recovered: fast-window "
                        f"burn rate {fast:.1f} < {thr:g}"))
                if self._burning.get(objective, False):
                    burning.append(objective)
        g = getattr(self.metrics, "slo_burn_rate", None)
        if g is not None:  # duck-typed: metrics fakes stay valid
            for val, objective, window in gauges:
                g.set(val, objective=objective, window=window)
        for reason, objective, message in emissions:
            self._emit(reason, objective, message)
        return ",".join(burning)

    def _emit(self, reason: str, objective: str, message: str) -> None:
        if self.event_sink is None:
            return
        from kubernetes_tpu.events import ObjectRef

        ref = ObjectRef(name=f"slo-{objective}",
                        involved_kind="Scheduler")
        try:
            self.event_sink(reason, ref, message)
        except Exception:
            pass  # a broken sink must never take the cycle down

    def burning(self) -> bool:
        with self._lock:
            return any(self._burning.values())

    def burns_total(self) -> int:
        with self._lock:
            return sum(self.burns.values())

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "objectives": [o for o, _ in self.objectives()],
                "burning": sorted(o for o, b in self._burning.items()
                                  if b),
                "burns": dict(self.burns),
                "cost_baseline_s": {
                    k: round(v, 6)
                    for k, v in sorted(self._baseline.items())},
            }


class PerfLedger:
    """The facade: measured distributions + cost model + watchdog, one
    ``observe_cycle`` call from ``Observability.end_cycle`` per eventful
    cycle (zero device syncs), one thread-safe ``snapshot`` for
    ``/debug/ledger``."""

    def __init__(self, config=None, metrics=None,
                 clock: Callable[[], float] = time.monotonic,
                 lock_factory=None) -> None:
        if config is None:
            from kubernetes_tpu.config import LedgerConfig

            config = LedgerConfig()
        self.config = config
        self.metrics = metrics
        self.clock = clock
        self.model = CycleCostModel(lock_factory=lock_factory)
        self.watchdog = SLOWatchdog(config, clock=clock, metrics=metrics,
                                    lock_factory=lock_factory)
        self._lock = make_lock(lock_factory, "obs.ledger")
        self.entries: deque = deque(maxlen=max(1, int(config.history)))
        #: (phase, scope, mesh) -> RollingDist
        self._dists: Dict[Tuple[str, str, int], RollingDist] = {}
        #: phase labels ever exported on the attribution gauge — the
        #: explain-gauge freshness rule: phases that stop firing zero
        self._phases_seen: set = set()
        #: lifetime observed cycles (eviction observable like the
        #: flight recorder's recorded - len)
        self.observed = 0
        #: clock stamp of the last pressure-probe re-evaluation:
        #: request threads only need burn RECOVERY to land within
        #: ~a second, not a full state-machine pass per mutating call
        self._last_probe_eval = float("-inf")

    @property
    def enabled(self) -> bool:
        return bool(getattr(self.config, "enabled", True))

    @property
    def event_sink(self):
        return self.watchdog.event_sink

    @event_sink.setter
    def event_sink(self, sink) -> None:
        self.watchdog.event_sink = sink

    def pressure_engaged(self) -> bool:
        """True while a sustained burn should inflate
        ``Scheduler.backend_pressure`` (APF sheds earlier). While
        burning, the windows re-evaluate HERE too: observe_cycle only
        runs on eventful cycles, so a queue that drains after a burn
        would otherwise freeze the degraded state (and the recovery
        event) until the next eventful cycle — possibly never."""
        if not (self.enabled
                and bool(getattr(self.config, "engage_pressure", True))):
            return False
        if not self.watchdog.objectives():
            # lock-free config read: with both objectives off (the
            # shipped default) the watchdog can never burn — keep the
            # per-mutating-request probe contention-free
            return False
        if self.watchdog.burning():
            # throttled: the probe rides the request path on every
            # mutating call while degraded — bounded-staleness (1 s)
            # recovery beats an evaluate per request (races on the
            # stamp are benign: worst case one extra evaluate)
            now = self.clock()
            if now - self._last_probe_eval >= PRESSURE_EVAL_INTERVAL_S:
                self._last_probe_eval = now
                self.watchdog.evaluate(now, allow_trip=False)
        return self.watchdog.burning()

    def tick(self) -> None:
        """Idle-path evaluation (Scheduler.idle_tick): keep the
        burn-rate windows — and the recovery transition — live while no
        eventful cycle arrives to run observe_cycle. Recovery only
        (``allow_trip=False``): idle window drainage must never START
        a burn on stale samples."""
        if self.enabled and self.watchdog.objectives():
            self.watchdog.evaluate(self.clock(), allow_trip=False)

    # -- per-cycle accounting ----------------------------------------------

    def observe_cycle(self, rec, res=None,
                      spans=None) -> Optional[LedgerEntry]:
        """Fold one finished cycle in; returns the LedgerEntry (None
        when disabled). ``rec`` is the CycleRecord ``end_cycle`` just
        built; ``res`` the CycleResult (e2e latency source); ``spans``
        the trace's CHILD-EXCLUSIVE durations (Trace.self_durations) so
        phases are disjoint — a nested validate must not count under
        both 'solve' and 'validate'. Falls back to the record's
        inclusive spans for callers without a trace (replays, tests)."""
        if not self.enabled:
            return None
        if spans is None:
            spans = rec.spans
        phases: Dict[str, float] = {}
        for name, dur in (spans or {}).items():
            ph = phase_of(name)
            if ph:
                phases[ph] = phases.get(ph, 0.0) + float(dur)
        P, N = parse_batch_shape(rec.batch_shape)
        scope = rec.solve_scope or ("full" if rec.tier else "")
        solve_s = phases.get("solve", 0.0) + phases.get("dispatch", 0.0)
        rounds = max(int(getattr(res, "rounds", 0) or 0), 1)
        modeled, basis, eff = -1.0, "", -1.0
        if solve_s > 0 and P > 0:
            # offer this cycle as the rate anchor FIRST (best rate
            # wins): without a warmup anchor the first cycles
            # self-calibrate, and a faster-than-ever cycle re-bases the
            # speed-of-light reference before being judged against it
            self_anchored = self.model.record_anchor(
                scope, P, N, rec.mesh, solve_s, rounds=rounds)
            pred, basis = self.model.predict(P, N, rec.mesh, scope,
                                             rounds=rounds)
            if pred is None:
                pred, basis = solve_s, "anchor"
            elif self_anchored:
                # THIS cycle is the reference it was judged against —
                # efficiency 1.0 by construction, labeled so operators
                # can tell a degenerate self-comparison from a real
                # calibrated prediction
                basis = "anchor"
            modeled = float(pred)
            # clipped: a pathological model must not mint absurd gauges
            eff = min(max(modeled / solve_s, 0.0), 8.0)
        e2e = list(res.e2e_latency_s.values()) if (
            res is not None and getattr(res, "e2e_latency_s", None)) else []
        slo = self.watchdog.observe_cycle(rec.t, e2e, solve_s, scope)
        entry = LedgerEntry(
            cycle=rec.cycle, t=rec.t, batch_shape=rec.batch_shape,
            scope=scope, mesh=rec.mesh, phases=phases,
            measured_s=rec.elapsed_s, solve_s=solve_s, modeled_s=modeled,
            efficiency=eff, model_basis=basis, slo=slo,
        )
        with self._lock:
            self.entries.append(entry)
            self.observed += 1
            for ph, dur in phases.items():
                cell = self._dists.get((ph, scope, rec.mesh))
                if cell is None:
                    cell = self._dists[(ph, scope, rec.mesh)] = RollingDist(
                        window=self.config.dist_window,
                        alpha=self.config.baseline_decay)
                cell.observe(dur)
        self._publish(entry, phases)
        return entry

    def _publish(self, entry: LedgerEntry, phases: Dict[str, float]) -> None:
        m = self.metrics
        if m is None:
            return
        # duck-typed like every metrics attach: partial fakes stay
        # valid. Freshness: a solve-free cycle writes the -1 sentinel
        # instead of leaving a stale older cycle's verdict on the wire
        # (the same rule the phase gauge follows below).
        g_eff = getattr(m, "cycle_model_efficiency", None)
        if g_eff is not None:
            g_eff.set(round(entry.efficiency, 4)
                      if entry.efficiency >= 0 else -1.0)
        g_mod = getattr(m, "cycle_modeled_cost", None)
        if g_mod is not None:
            g_mod.set(round(entry.modeled_s, 6)
                      if entry.modeled_s >= 0 else -1.0)
        g_ph = getattr(m, "cycle_phase_seconds", None)
        if g_ph is not None:
            for ph, dur in phases.items():
                g_ph.set(round(dur, 6), phase=ph)
            # freshness: a phase the cycle did not run reads 0, not the
            # last cycle that happened to run it (explain-gauge rule)
            for ph in self._phases_seen - set(phases):
                g_ph.set(0.0, phase=ph)
            self._phases_seen |= set(phases)

    # -- export -------------------------------------------------------------

    def snapshot(self) -> dict:
        """The /debug/ledger body (thread-safe, like /debug/why)."""
        with self._lock:
            entries = list(self.entries)
            dists = {
                f"{ph}|{scope or '-'}|mesh{mesh}": d.to_json()
                for (ph, scope, mesh), d in sorted(self._dists.items())}
            observed = self.observed
        effs = [e.efficiency for e in entries if e.efficiency >= 0]
        return {
            "observed": observed,
            "retained": len(entries),
            "model": self.model.snapshot(),
            "slo": self.watchdog.snapshot(),
            "model_efficiency": _dist_summary(effs),
            "distributions": dists,
            "entries": [e.to_json() for e in entries],
        }

    def arm_summary(self) -> dict:
        """The bench-record shape (scripts/bench_churn.py per-arm
        ``ledger`` block; scripts/bench_compare.py's ``ledger`` gate
        family reads exactly this): measured-vs-modeled efficiency,
        burn counts, and per-phase attribution shares."""
        with self._lock:
            entries = list(self.entries)
        effs = [e.efficiency for e in entries if e.efficiency >= 0]
        total = sum(e.measured_s for e in entries)
        phases: Dict[str, float] = {}
        for e in entries:
            for ph, dur in e.phases.items():
                phases[ph] = phases.get(ph, 0.0) + dur
        return {
            "cycles": len(entries),
            "model_efficiency": _dist_summary(effs),
            "phase_share": {
                ph: round(v / total, 4) if total > 0 else 0.0
                for ph, v in sorted(phases.items())},
            "slo": {"burns": self.watchdog.burns_total(),
                    "burning": self.watchdog.burning()},
        }


def _dist_summary(vals: List[float]) -> dict:
    if not vals:
        return {"n": 0}
    s = sorted(vals)
    return {"n": len(s), "mean": round(sum(s) / len(s), 4),
            "p50": round(_quantile(s, 0.5), 4),
            "p99": round(_quantile(s, 0.99), 4),
            "min": round(s[0], 4)}
