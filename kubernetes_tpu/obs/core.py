"""The Observability facade the scheduler owns: one object tying the
cycle tracer, the JAX telemetry counters, and the flight recorder to the
typed config (:class:`kubernetes_tpu.config.ObservabilityConfig`) and
the metrics registry.

Lifecycle per scheduling cycle::

    trace = obs.begin_cycle(cycle_no)     # always returns a Trace
    with obs.span("snapshot"): ...        # nested spans on that trace
    obs.note_batch_shape("P8xN5")         # scratch notes for the record
    obs.end_cycle(res)                    # -> CycleRecord + trace ring

Trace retention is SAMPLED (``trace_sampling`` — deterministic,
counter-based, no RNG: the k-th EVENTFUL cycle is retained when
``floor(k*rate)`` advances; idle polls don't consume sampling slots),
but the trace object itself always exists so
``log_if_long`` keeps its always-on cheap-profiler role. Everything
runs on the injected clock; nothing here touches device values except
:meth:`end_cycle`'s single sinkhorn-stats readback, which happens at the
cycle's host boundary alongside the driver's own readbacks."""

from __future__ import annotations

import json
import math
import time
from collections import deque
from contextlib import contextmanager, nullcontext
from typing import Callable, List, Optional

from kubernetes_tpu.obs.incidents import IncidentRecorder
from kubernetes_tpu.obs.jaxtel import JaxTelemetry
from kubernetes_tpu.obs.journey import JourneyTracker
from kubernetes_tpu.obs.ledger import PerfLedger
from kubernetes_tpu.obs.memledger import MemoryLedger
from kubernetes_tpu.obs.recorder import CycleRecord, FlightRecorder
from kubernetes_tpu.obs.trace import Trace, chrome_trace_json
from kubernetes_tpu.sanitize import make_lock


class Observability:
    def __init__(self, config=None, metrics=None,
                 clock: Callable[[], float] = time.monotonic,
                 lock_sanitizer=None) -> None:
        if config is None:
            from kubernetes_tpu.config import ObservabilityConfig

            config = ObservabilityConfig()
        self.config = config
        self.metrics = metrics
        self.clock = clock
        #: runtime lock sanitizer (kubernetes_tpu/sanitize.py) — when the
        #: scheduler armed one, every obs-side lock is built through it
        #: so the acquisition-order graph covers the whole facade
        self.lock_sanitizer = lock_sanitizer
        lf = lock_sanitizer.factory() if lock_sanitizer is not None else None
        self.jax = JaxTelemetry(
            metrics=metrics,
            storm_threshold=config.retrace_storm_threshold,
            storm_window=config.retrace_storm_window,
            lock_factory=lf,
        )
        self.recorder = FlightRecorder(config.recorder_capacity,
                                       lock_factory=lf)
        #: perf ledger + SLO watchdog (obs/ledger.py): consumes each
        #: eventful cycle's record at end_cycle — measured phase
        #: distributions, measured-vs-modeled efficiency, burn-rate
        #: objectives. getattr: duck-typed config fakes stay valid;
        #: PerfLedger itself defaults a None config to LedgerConfig().
        self.ledger = PerfLedger(getattr(config, "ledger", None),
                                 metrics=metrics, clock=clock,
                                 lock_factory=lf)
        #: device-memory ledger (obs/memledger.py): modeled resident
        #: accounting + cycle-boundary measured sampling + the
        #: preflight peak table + OOM forensics. Same duck-typed
        #: config attach as the perf ledger.
        self.memledger = MemoryLedger(getattr(config, "memory_ledger",
                                              None),
                                      metrics=metrics, clock=clock,
                                      lock_factory=lf)
        #: per-pod journey tracer (obs/journey.py): fed by the queue
        #: and driver seams, read by /debug/journeys and the incident
        #: bundles. Same duck-typed config attach as the ledgers.
        self.journeys = JourneyTracker(getattr(config, "journeys", None),
                                       metrics=metrics, clock=clock,
                                       lock_factory=lf)
        #: incident autopsies (obs/incidents.py): evaluates its five
        #: triggers against each eventful cycle record at end_cycle;
        #: the evidence sources are the sibling sub-objects above.
        self.incidents = IncidentRecorder(
            getattr(config, "incidents", None), metrics=metrics,
            clock=clock, lock_factory=lf, recorder=self.recorder,
            ledger=self.ledger, memledger=self.memledger, jaxtel=self.jax,
            journeys=self.journeys)
        self.traces: deque = deque(maxlen=max(1, config.trace_ring_capacity))
        #: guards the traces ring: the scheduler thread appends while the
        #: /debug/traces handler thread snapshots (deque iteration during
        #: an append raises RuntimeError)
        self._traces_lock = make_lock(lf, "obs.traces")
        self.current_trace: Optional[Trace] = None
        self.last_trace: Optional[Trace] = None
        #: EVENTFUL cycles seen — the trace-sampling sequence. Idle
        #: serve-loop polls must not consume sampling slots: a workload
        #: phase-locked with the poll period (pods landing every other
        #: poll) would otherwise park every eventful cycle on the
        #: unsampled phase and retain nothing, forever.
        self._eventful_seq = 0
        # per-cycle scratch, reset by begin_cycle
        self._scratch: dict = {}
        self._sinkhorn_stats = None  # device (2,) [iters, residual]
        self._retraces_at_begin = 0
        #: takeover reconciliation happens BETWEEN cycles — the flag
        #: parks here until the next begin_cycle stamps it onto that
        #: cycle's record (value = elector epoch, or 1 when unknown)
        self._pending_takeover = 0
        #: state-conservation audits run BETWEEN cycles too (the serving
        #: runtime's low-frequency sweep, the chaos harnesses) — their
        #: violation count parks here until the next record, same
        #: between-cycles pattern as the takeover flag
        self._pending_invariants = 0
        #: OOM forensic flags captured BETWEEN cycles (warmup aborts)
        #: park here until the next begin_cycle stamps them, same
        #: pattern as the takeover flag
        self._pending_oom = ""
        #: sharded-backend provenance: device count of the scheduler's
        #: node-axis mesh (0 = single-device). Set once at construction
        #: (note_mesh); stamped on every cycle's flight record so a
        #: postmortem knows which records ran sharded.
        self.mesh_devices = 0

    # -- cycle lifecycle ----------------------------------------------------

    def _sampled(self, seq: int) -> bool:
        rate = min(max(float(self.config.trace_sampling), 0.0), 1.0)
        if rate <= 0.0:
            return False
        return math.floor(seq * rate) > math.floor((seq - 1) * rate)

    def begin_cycle(self, cycle: int = 0) -> Trace:
        self._scratch = {"cycle": cycle, "t": self.clock(),
                         "breakers": [], "retries": 0,
                         "deadline_exceeded": False,
                         "takeover": self._pending_takeover,
                         "device_resets": 0, "fenced_binds": 0,
                         "invariant_violations": self._pending_invariants,
                         "ambiguous_binds": 0,
                         "oom_forensic": self._pending_oom}
        self._pending_takeover = 0
        self._pending_invariants = 0
        self._pending_oom = ""
        self._sinkhorn_stats = None
        self._retraces_at_begin = self.jax.retrace_total()
        self._d2h_at_begin = self.jax.d2h_bytes_total()
        self._lockfind_at_begin = (
            self.lock_sanitizer.total_findings()
            if self.lock_sanitizer is not None else 0)
        self.current_trace = Trace("Scheduling cycle", clock=self.clock,
                                   cycle=cycle)
        return self.current_trace

    def span(self, name: str, **fields):
        """Nested span on the in-flight cycle trace (no-op outside a
        cycle — extender/shim instrumentation stays safe standalone)."""
        if self.current_trace is None:
            return nullcontext()
        return self.current_trace.span(name, **fields)

    def step(self, msg: str) -> None:
        if self.current_trace is not None:
            self.current_trace.step(msg)

    # -- scratch notes (cycle-scoped inputs to the flight record) -----------

    def note_cycle(self, cycle: int) -> None:
        """Stamp the real cycle number (known only after pop_batch —
        begin_cycle ran before the queue incremented it) on the record
        AND the in-flight trace, so /debug/traces and
        /debug/flightrecorder agree on which cycle a span belongs to."""
        self._scratch["cycle"] = cycle
        tr = self.current_trace
        if tr is not None:
            tr.fields["cycle"] = cycle
            tr.root.fields["cycle"] = cycle

    def note_batch_shape(self, digest: str) -> None:
        self._scratch["batch_shape"] = digest

    def note_breaker(self, target: str, old: str, new: str) -> None:
        if "breakers" in self._scratch:
            self._scratch["breakers"].append((target, old, new))

    def note_retry(self) -> None:
        self._scratch["retries"] = self._scratch.get("retries", 0) + 1

    def note_deadline_exceeded(self) -> None:
        self._scratch["deadline_exceeded"] = True

    def note_snapshot(self, mode: str, rows: int) -> None:
        """How the cycle's device snapshot was produced (full | delta |
        clean) and how many node rows it re-packed — the per-cycle
        observability of 'cost proportional to what changed'."""
        self._scratch["snapshot_mode"] = mode
        self._scratch["snapshot_rows"] = rows

    def note_solve_scope(self, scope: str, reuse_frac: float = 0.0) -> None:
        """Which solve the cycle ran (restricted | full) and how much of
        the cached score plane it reused — the incremental-solve
        provenance (``scope=`` flight-record flag)."""
        self._scratch["solve_scope"] = scope
        self._scratch["reuse_frac"] = float(reuse_frac)

    def note_microbatch(self, trigger: str, window_s: float) -> None:
        """The serving loop's micro-batch provenance for this cycle:
        what flushed the accumulation window (bucket-fill | max-wait)
        and how long it held — so a latency incident in the flight
        record separates window time from solve time."""
        self._scratch["flush_trigger"] = trigger
        self._scratch["window_s"] = window_s

    def note_takeover(self, epoch: int = 1) -> None:
        """A takeover / cold-start reconciliation ran (between cycles):
        flag the NEXT cycle's flight record with ``takeover=epoch...``
        so a postmortem can see which cycles ran under which
        incarnation."""
        self._pending_takeover = max(int(epoch), 1)

    def note_device_reset(self) -> None:
        """The resident device snapshot was dropped + rebuilt after a
        device error this cycle (``device_reset=`` flight-record flag)."""
        if "device_resets" in self._scratch:
            self._scratch["device_resets"] = (
                self._scratch.get("device_resets", 0) + 1)

    def note_fenced_bind(self) -> None:
        """A bind was aborted by the lease fence this cycle (``fenced=``
        flight-record flag)."""
        if "fenced_binds" in self._scratch:
            self._scratch["fenced_binds"] = (
                self._scratch.get("fenced_binds", 0) + 1)

    def note_invariant_violations(self, n: int = 1) -> None:
        """The state-conservation auditor (obs/audit.py) found ``n``
        violations — stamp the in-flight cycle's record (``invariants=``
        flag), or park for the next one when the audit ran between
        cycles (the serving runtime's low-frequency sweep)."""
        if "invariant_violations" in self._scratch and \
                self.current_trace is not None:
            self._scratch["invariant_violations"] = (
                self._scratch.get("invariant_violations", 0) + int(n))
        else:
            self._pending_invariants += int(n)

    def note_ambiguous_bind(self) -> None:
        """A bind RPC timed out ambiguously this cycle and went through
        read-your-write resolution (``ambig=`` flight-record flag)."""
        if "ambiguous_binds" in self._scratch:
            self._scratch["ambiguous_binds"] = (
                self._scratch.get("ambiguous_binds", 0) + 1)

    def note_mesh(self, devices: int) -> None:
        """The sharded execution backend's mesh size (``mesh=N`` flag on
        every flight record; 0 = single-device)."""
        self.mesh_devices = int(devices)

    def note_mesh_cycle(self, devices: int) -> None:
        """What THIS cycle actually ran on: 0 during the device-loss
        cooloff's single-device host-mode fallback even when the
        scheduler owns a mesh — so the flight record's ``mesh=`` flag
        stays truthful per cycle, not per construction."""
        self._scratch["mesh"] = int(devices)

    def note_preflight(self, action: str) -> None:
        """The memory preflight's verdict for this cycle's shape
        (ok | split | shed — ``preflight=`` flight-record flag when it
        engaged)."""
        self._scratch["preflight"] = action

    def note_oom_forensic(self, flag: str) -> None:
        """A DeviceOOM / device-loss forensic record was captured this
        cycle (obs/memledger.record_oom): its ``mem=`` flag text lands
        on the cycle's flight record, routing a postmortem to
        /debug/memory. Between-cycles captures (warmup aborts) park for
        the next record, same pattern as the takeover flag."""
        if self.current_trace is not None:
            self._scratch["oom_forensic"] = flag
        else:
            self._pending_oom = flag

    def note_sinkhorn(self, stats) -> None:
        """Stash the solver's (iters, residual) device pair; read back
        once at end_cycle (the cycle's host boundary)."""
        self._sinkhorn_stats = stats

    def note_scenario(self, scores: dict) -> None:
        """The cycle's scenario placement-quality scores (already
        decoded at the host boundary by the driver); land on the flight
        record as the ``scenario`` block."""
        self._scratch["scenario"] = dict(scores)

    def note_explain(self, report) -> None:
        """Stash the cycle's UnschedulableReport (already decoded at the
        host boundary by the driver); the flight record keeps its top-K
        reasons."""
        self._scratch["explain"] = report

    # -- cycle close --------------------------------------------------------

    def end_cycle(self, res=None) -> Optional[CycleRecord]:
        trace = self.current_trace
        self.current_trace = None
        if trace is None:
            return None
        trace.finish()
        self.last_trace = trace
        sk_iters = sk_resid = -1.0
        if self._sinkhorn_stats is not None:
            # the one device readback this module performs — at the host
            # boundary, next to the driver's own result readbacks.
            # [-1, -1] is the solver's "plan never engaged" sentinel
            # (argmax rounds all the way): not a convergence sample.
            arr = self.jax.readback("sinkhorn-stats", self._sinkhorn_stats)
            if float(arr[0]) >= 0:
                sk_iters, sk_resid = float(arr[0]), float(arr[1])
                if self.metrics is not None:
                    self.metrics.sinkhorn_iterations.observe(sk_iters)
                    self.metrics.sinkhorn_residual.set(sk_resid)
            self._sinkhorn_stats = None
        if not self.config.enabled:
            return None
        s = self._scratch
        # idle poll cycles (empty batch, nothing attempted, no incident
        # activity) are not black-box material: recording them would let
        # ~a minute of idle 0.25s serve-loop polls evict every record of
        # the incident the recorder exists to explain
        attempted = getattr(res, "attempted", 0) if res is not None else 0
        lock_findings = (
            self.lock_sanitizer.total_findings()
            - getattr(self, "_lockfind_at_begin", 0)
            if self.lock_sanitizer is not None else 0)
        eventful = bool(
            attempted
            or s.get("retries", 0)
            or s.get("deadline_exceeded", False)
            or s.get("breakers")
            or s.get("takeover", 0)
            or s.get("device_resets", 0)
            or s.get("fenced_binds", 0)
            or s.get("invariant_violations", 0)
            or s.get("ambiguous_binds", 0)
            or s.get("oom_forensic", "")
            or lock_findings
        )
        if not eventful:
            return None
        rec = CycleRecord(
            cycle=s.get("cycle", 0),
            t=s.get("t", 0.0),
            batch_shape=s.get("batch_shape", ""),
            tier=getattr(res, "solver_tier", "") if res is not None else "",
            fallbacks=(getattr(res, "solver_fallbacks", 0)
                       if res is not None else 0),
            retries=s.get("retries", 0),
            deadline_exceeded=s.get("deadline_exceeded", False),
            breaker_transitions=list(s.get("breakers", ())),
            attempted=getattr(res, "attempted", 0) if res is not None else 0,
            scheduled=getattr(res, "scheduled", 0) if res is not None else 0,
            unschedulable=(getattr(res, "unschedulable", 0)
                           if res is not None else 0),
            elapsed_s=getattr(res, "elapsed_s", 0.0) if res is not None else 0.0,
            spans=trace.span_durations(),
            retraces=self.jax.retrace_total() - self._retraces_at_begin,
            readback_bytes=(self.jax.d2h_bytes_total()
                            - getattr(self, "_d2h_at_begin", 0)),
            sinkhorn_iters=sk_iters,
            sinkhorn_residual=sk_resid,
            top_reasons=(
                s["explain"].top_reasons(
                    getattr(self.config, "explain_top_k", 3))
                if s.get("explain") is not None else []
            ),
            snapshot_mode=s.get("snapshot_mode", ""),
            snapshot_rows=s.get("snapshot_rows", 0),
            solve_scope=s.get("solve_scope", ""),
            reuse_frac=s.get("reuse_frac", 0.0),
            pipeline_chunks=(getattr(res, "pipeline_chunks", 0)
                             if res is not None else 0),
            flush_trigger=s.get("flush_trigger", ""),
            window_s=s.get("window_s", 0.0),
            takeover=s.get("takeover", 0),
            device_resets=s.get("device_resets", 0),
            fenced_binds=s.get("fenced_binds", 0),
            invariant_violations=s.get("invariant_violations", 0),
            ambiguous_binds=s.get("ambiguous_binds", 0),
            lock_findings=lock_findings,
            mesh=s.get("mesh", self.mesh_devices),
            scenario=s.get("scenario", {}),
            preflight=s.get("preflight", ""),
            oom_forensic=s.get("oom_forensic", ""),
        )
        # perf ledger (obs/ledger.py): fold the cycle's measured phase
        # costs in, confront them with the cost model, run the SLO
        # watchdog — then stamp the verdict back onto the record, the
        # CycleResult, and the trace's Perfetto counter track. Pure
        # host math over the spans already collected: zero new syncs.
        # Phase attribution uses CHILD-EXCLUSIVE durations (a validate
        # nested inside solve:batch counts once); the record keeps the
        # inclusive view it documents.
        entry = self.ledger.observe_cycle(rec, res,
                                          spans=trace.self_durations())
        if entry is not None:
            rec.slo = entry.slo
            if entry.efficiency >= 0:
                rec.modeled_s = entry.modeled_s
                rec.model_efficiency = entry.efficiency
                rec.model_basis = entry.model_basis
                if res is not None:
                    res.modeled_s = entry.modeled_s
                    res.model_efficiency = entry.efficiency
                trace.counter("model_efficiency", eff=entry.efficiency)
        # device-memory ledger (obs/memledger.py): the cycle-boundary
        # measured sample + the modeled-vs-measured confrontation —
        # host metadata reads only, zero new syncs (the freshness/-1
        # sentinel rules mirror the perf ledger's verdict above)
        mentry = self.memledger.observe_cycle(rec)
        if mentry is not None:
            rec.mem_modeled_bytes = mentry["modeled_bytes"]
            rec.mem_measured_bytes = mentry["measured_bytes"]
            rec.mem_efficiency = mentry["efficiency"]
        self.recorder.record(rec)
        # incident triggers (obs/incidents.py): every trigger is
        # derived from state already in hand — the watchdog's burn
        # counter, the jaxtel storm counters, and the record's own
        # violation/OOM/fallback fields — so evaluation adds no
        # scheduler seams and no syncs. Runs AFTER recorder.record so
        # the bundle's flight window includes the trigger cycle itself.
        self.incidents.observe_cycle(rec)
        self._eventful_seq += 1
        if self._sampled(self._eventful_seq):
            with self._traces_lock:
                self.traces.append(trace)
        return rec

    # -- export / debug endpoints -------------------------------------------

    def chrome_trace(self) -> dict:
        """Chrome trace-event document over the retained trace ring."""
        with self._traces_lock:
            traces = list(self.traces)
        return chrome_trace_json(traces)

    def export_chrome_trace(self) -> str:
        return json.dumps(self.chrome_trace())

    def debug_payload(self) -> dict:
        """The /debug/flightrecorder body: recorder ring + JAX telemetry."""
        return {
            "flight_recorder": self.recorder.to_json(),
            "jax": self.jax.snapshot(),
        }
