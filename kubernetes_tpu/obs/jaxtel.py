"""Runtime JAX telemetry — compile-cache and transfer accounting the
static linter (kubernetes_tpu/lint) cannot see.

graftlint's R3 catches jit-in-a-loop *statically*; this module measures
the dynamic twin: whether the arguments a call site actually feeds its
jitted kernel keep the same abstract signature (shapes + dtypes +
static keys) call over call. A new signature at a known site is a
retrace (XLA recompiles); many retraces inside a short call window is a
retrace STORM — the exact failure mode bucketed batch shapes
(utils/interner.bucket_size) exist to prevent.

Everything here runs on the HOST side of the boundary, *before* the
jitted call: the digest reads only ``.shape``/``.dtype`` metadata (no
device sync), so instrumentation adds zero host syncs inside jitted
code — the lint gate stays green by construction.

Transfer accounting rides the same idea: :meth:`JaxTelemetry.readback`
wraps the ``np.asarray(...)`` host boundaries the driver already
declares, charging the bytes moved to a named site, and
:meth:`record_transfer` counts host->device uploads from array metadata.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, Optional, Tuple

import numpy as np

from kubernetes_tpu.sanitize import make_lock


def _leaf_sig(x) -> object:
    """Abstract signature of one pytree leaf: (shape, dtype) for anything
    array-like, the value itself for hashable host scalars, else repr."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype))
    try:
        hash(x)
        return x
    except TypeError:
        return repr(x)


def abstract_digest(*trees, static=None) -> Tuple:
    """Hashable digest of the abstract (shape/dtype) signature of the
    given pytrees plus a static key — what jax's compile cache keys on
    for the dynamic arguments. Reads metadata only: no device sync."""
    import jax

    sigs = []
    for t in trees:
        if t is None:
            sigs.append(None)
            continue
        leaves = jax.tree_util.tree_leaves(t)
        sigs.append(tuple(_leaf_sig(x) for x in leaves))
    return (tuple(sigs), static)


def tree_nbytes(*trees) -> int:
    """Total byte size of every array-like leaf (metadata only)."""
    import jax

    total = 0
    for t in trees:
        if t is None:
            continue
        for x in jax.tree_util.tree_leaves(t):
            shape = getattr(x, "shape", None)
            dtype = getattr(x, "dtype", None)
            if shape is None or dtype is None:
                continue
            n = 1
            for d in shape:
                n *= int(d)
            total += n * np.dtype(str(dtype)).itemsize
    return total


class JaxTelemetry:
    """Per-site compile-cache observation + transfer accounting.

    ``record_call(site, *trees, static=...)`` classifies the call:

    - first digest ever seen at the site -> "compile" (cold miss);
    - digest already seen -> "hit";
    - NEW digest at a site that already compiled -> "retrace" (the
      counter the acceptance gate pins: exactly one increment when the
      batch shape changes).

    Retrace storms: >= ``storm_threshold`` retraces within the last
    ``storm_window`` calls at one site increments the storm counter once
    per crossing (the window then resets) — deterministic, count-based,
    no wall clock."""

    def __init__(self, metrics=None, storm_threshold: int = 8,
                 storm_window: int = 64,
                 signature_capacity: int = 4096,
                 lock_factory=None) -> None:
        self.metrics = metrics
        self.storm_threshold = max(1, int(storm_threshold))
        self.storm_window = max(1, int(storm_window))
        #: per-site cap on retained signatures — a sustained retrace
        #: storm mints a new digest every cycle, and an unbounded set
        #: would leak for as long as the pathology lasts (the recorder
        #: and trace rings are hard-bounded for the same reason). LRU:
        #: evicting a signature only means its NEXT appearance counts as
        #: a retrace again, which under a storm it effectively is.
        self.signature_capacity = max(1, int(signature_capacity))
        #: site -> insertion-ordered {digest: None} used as an LRU set
        self._seen: Dict[str, dict] = {}
        #: one lock for every counter dict: record_call/record_transfer
        #: run on the scheduler thread while snapshot() serves the
        #: /debug/flightrecorder handler thread — an unlocked dict
        #: iteration there can raise "dictionary changed size during
        #: iteration" mid-incident
        self._lock = make_lock(lock_factory, "obs.jaxtel")
        self.calls: Dict[str, int] = {}
        self.hits: Dict[str, int] = {}
        self.compiles: Dict[str, int] = {}
        self.retraces: Dict[str, int] = {}
        self.storms: Dict[str, int] = {}
        #: call indices (per site) of recent retraces, for the storm window
        self._retrace_at: Dict[str, deque] = {}
        #: (site, direction) -> [count, bytes]
        self.transfers: Dict[Tuple[str, str], list] = {}

    # -- compile cache ------------------------------------------------------

    def record_call(self, site: str, *trees, static=None,
                    warmup: bool = False) -> str:
        """Record one jitted-call observation; returns the class
        ("hit" | "compile" | "retrace"). ``warmup=True`` registers an
        AHEAD-OF-TIME compile (Scheduler.warmup's bucket sweep): a new
        signature there counts as a deliberate compile, never a retrace —
        retraces exist to flag recompiles sneaking onto the hot path."""
        digest = abstract_digest(*trees, static=static)
        with self._lock:
            seen = self._seen.setdefault(site, {})
            n = self.calls.get(site, 0) + 1
            self.calls[site] = n
            stormed = False
            if digest in seen:
                kind = "hit"
                self.hits[site] = self.hits.get(site, 0) + 1
                seen.pop(digest)  # re-inserted below as most-recent
            elif warmup or (not seen and not self.compiles.get(site)):
                kind = "compile"
                self.compiles[site] = self.compiles.get(site, 0) + 1
            else:
                kind = "retrace"
                self.retraces[site] = self.retraces.get(site, 0) + 1
                window = self._retrace_at.setdefault(site, deque())
                window.append(n)
                while window and n - window[0] >= self.storm_window:
                    window.popleft()
                if len(window) >= self.storm_threshold:
                    self.storms[site] = self.storms.get(site, 0) + 1
                    window.clear()
                    stormed = True
            seen[digest] = None
            while len(seen) > self.signature_capacity:
                seen.pop(next(iter(seen)))
        m = self.metrics
        if m is not None:
            m.jax_compile_cache.inc(site=site, result=kind)
            if kind == "retrace":
                m.jax_retraces.inc(site=site)
            if stormed:
                m.jax_retrace_storms.inc(site=site)
        return kind

    def retrace_total(self, site: Optional[str] = None) -> int:
        with self._lock:
            if site is not None:
                return self.retraces.get(site, 0)
            return sum(self.retraces.values())

    def storm_total(self, site: Optional[str] = None) -> int:
        """Storm detections across all sites (or one) — the incident
        recorder's per-cycle delta source, same locking as
        :meth:`retrace_total`."""
        with self._lock:
            if site is not None:
                return self.storms.get(site, 0)
            return sum(self.storms.values())

    # -- transfers ----------------------------------------------------------

    def record_transfer(self, site: str, direction: str, nbytes: int) -> None:
        """Charge ``nbytes`` moved across the device boundary to a site.
        ``direction``: "h2d" (upload) or "d2h" (readback)."""
        with self._lock:
            row = self.transfers.setdefault((site, direction), [0, 0])
            row[0] += 1
            row[1] += int(nbytes)
        if self.metrics is not None:
            self.metrics.host_transfer_bytes.inc(
                int(nbytes), site=site, direction=direction)
            self.metrics.host_transfers.inc(site=site, direction=direction)
            if direction == "d2h":
                # the readback wall's dedicated meter (one label, so a
                # dashboard sums sites without direction filtering);
                # duck-typed so partial metrics fakes stay valid
                rb = getattr(self.metrics, "readback_bytes", None)
                if rb is not None:
                    rb.inc(int(nbytes), site=site)

    def d2h_bytes_total(self) -> int:
        """Total d2h bytes across every site — the flight recorder diffs
        this per cycle into CycleRecord.readback_bytes."""
        with self._lock:
            return sum(row[1] for (site, d), row in self.transfers.items()
                       if d == "d2h")

    def readback(self, site: str, x):
        """The declared d2h host boundary: materialize ``x`` — a single
        array or a pytree of arrays (NamedTuple structure preserved) —
        on host in one ``jax.device_get`` and account the total bytes as
        ONE transfer at the site, instead of one sync + one accounting
        entry per leaf."""
        import jax

        host = jax.device_get(x)
        nbytes = sum(
            np.asarray(leaf).nbytes
            for leaf in jax.tree_util.tree_leaves(host))
        self.record_transfer(site, "d2h", nbytes)
        return host

    def record_upload(self, site: str, *trees) -> None:
        """Account an h2d upload from array metadata (no sync)."""
        self.record_transfer(site, "h2d", tree_nbytes(*trees))

    # -- introspection ------------------------------------------------------

    def signature_count(self, site: Optional[str] = None) -> int:
        """Retained signature-LRU size — per site, or summed across all
        sites. Locked: the soak sentinel samples from the maintenance
        thread while record_call inserts on the scheduler thread. Each
        per-site set is capped at ``signature_capacity``, so this total
        is bounded by sites x capacity; the sentinel watches it anyway
        because an unexpected NEW site minted per phase would still grow
        it without bound."""
        with self._lock:
            if site is not None:
                return len(self._seen.get(site, ()))
            return sum(len(s) for s in self._seen.values())

    def snapshot(self) -> dict:
        """JSON-shaped view for /debug endpoints and the flight
        recorder; locked — the handler thread reads while the scheduler
        thread inserts new sites."""
        with self._lock:
            return {
                "sites": {
                    site: {
                        "calls": self.calls.get(site, 0),
                        "hits": self.hits.get(site, 0),
                        "compiles": self.compiles.get(site, 0),
                        "retraces": self.retraces.get(site, 0),
                        "storms": self.storms.get(site, 0),
                    }
                    for site in sorted(self.calls)
                },
                "transfers": {
                    f"{site}:{direction}": {"count": row[0], "bytes": row[1]}
                    for (site, direction), row in sorted(
                        self.transfers.items())
                },
            }
