"""Operation tracing — the ``k8s.io/utils/trace`` analog the reference
wraps around every scheduling cycle (``generic_scheduler.go:185``:
``utiltrace.New(...)`` + steps + ``LogIfLong(100ms)``), extended with
NESTED spans and a Chrome trace-event exporter.

A :class:`Trace` is a tree of :class:`Span` frames plus flat ``step``
marks (the utiltrace surface, kept verbatim for existing callers).
``log_if_long`` emits the breakdown through ``logging`` only when total
duration exceeds the threshold — the cheap always-on profiler for slow
cycles. ``to_chrome_events`` serializes the tree as trace-event
"complete" (``ph: "X"``) records so a cycle opens directly in
``chrome://tracing`` or Perfetto (nesting is reconstructed from ts/dur
containment on one pid/tid).

Everything here is host code on an injectable clock: deterministic under
fake clocks, no wall-clock reads beyond ``time.monotonic`` (graftlint R4
stays green)."""

from __future__ import annotations

import logging
import time
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger("kubernetes_tpu.trace")

#: the reference logs steps that took >= 50% of a (threshold/len) share;
#: we keep it simple: log everything when over threshold.
DEFAULT_THRESHOLD_S = 0.1  # LogIfLong(100*time.Millisecond)


class Span:
    """One timed frame. ``end is None`` while the frame is open; ``steps``
    are instant marks (utiltrace ``trace.Step``) inside this frame."""

    __slots__ = ("name", "start", "end", "fields", "children", "steps")

    def __init__(self, name: str, start: float, **fields) -> None:
        self.name = name
        self.start = start
        self.end: Optional[float] = None
        self.fields: Dict[str, object] = fields
        self.children: List["Span"] = []
        self.steps: List[Tuple[float, str]] = []

    def duration_s(self, now: Optional[float] = None) -> float:
        end = self.end if self.end is not None else now
        return max(0.0, (end if end is not None else self.start) - self.start)


class Trace:
    """utiltrace.Trace with nesting. The flat surface (``step`` /
    ``total_s`` / ``format`` / ``log_if_long``) matches the seed's
    utils.trace.Trace exactly; ``span`` adds nested timed frames."""

    def __init__(
        self,
        name: str,
        clock: Callable[[], float] = time.monotonic,
        **fields,
    ) -> None:
        self.name = name
        self.fields = fields
        self.clock = clock
        self.start = clock()
        self.root = Span(name, self.start, **fields)
        self._stack: List[Span] = [self.root]
        #: flat (timestamp, msg) list — the seed-compat view of steps
        self.steps: List[Tuple[float, str]] = []
        #: (timestamp, track name, {series: value}) counter samples —
        #: the Chrome trace "C" events (Perfetto counter tracks); the
        #: perf ledger stamps model efficiency here so it renders
        #: alongside the cycle's spans
        self.counters: List[Tuple[float, str, Dict[str, float]]] = []

    # -- utiltrace surface --------------------------------------------------

    def step(self, msg: str) -> None:
        t = self.clock()
        self.steps.append((t, msg))
        self._stack[-1].steps.append((t, msg))

    def total_s(self) -> float:
        return self.clock() - self.start

    def format(self) -> str:
        fields = ",".join(f"{k}={v}" for k, v in self.fields.items())
        lines = [f'Trace "{self.name}" ({fields}) total={self.total_s()*1000:.1f}ms:']
        prev = self.start
        for t, msg in self.steps:
            lines.append(f"  +{(t - prev)*1000:.1f}ms {msg}")
            prev = t
        now = self.clock()
        for child in self.root.children:
            self._format_span(child, lines, indent=1, now=now)
        return "\n".join(lines)

    def _format_span(self, span: Span, lines: List[str], indent: int,
                     now: float) -> None:
        pad = "  " * indent
        lines.append(
            f"{pad}[span] {span.name} {span.duration_s(now)*1000:.1f}ms"
            + ("" if not span.fields
               else " (" + ",".join(f"{k}={v}"
                                    for k, v in span.fields.items()) + ")")
        )
        for child in span.children:
            self._format_span(child, lines, indent + 1, now=now)

    def log_if_long(self, threshold_s: float = DEFAULT_THRESHOLD_S) -> Optional[str]:
        if self.total_s() >= threshold_s:
            text = self.format()
            logger.info(text)
            return text
        return None

    # -- nested spans -------------------------------------------------------

    def begin_span(self, name: str, **fields) -> Span:
        """Open a nested timed frame explicitly (driver loops that can't
        wrap a with-block); pair with :meth:`end_span`."""
        sp = Span(name, self.clock(), **fields)
        self._stack[-1].children.append(sp)
        self._stack.append(sp)
        return sp

    def end_span(self, sp: Span) -> None:
        sp.end = self.clock()
        # tolerate a span leaked open by re-entrant misuse: pop back to
        # (and including) this frame instead of corrupting the stack for
        # every later span
        while self._stack and self._stack[-1] is not sp:
            self._stack.pop()
        if self._stack:
            self._stack.pop()

    @contextmanager
    def span(self, name: str, **fields):
        """Open a nested timed frame; closes (records ``end``) on exit,
        including the exception path."""
        sp = self.begin_span(name, **fields)
        try:
            yield sp
        finally:
            self.end_span(sp)

    def counter(self, name: str, **values: float) -> None:
        """Record a counter-track sample (Chrome trace "C" event) at
        the current clock — values render as a stacked counter track in
        Perfetto, aligned with this trace's spans."""
        self.counters.append(
            (self.clock(), name, {k: float(v) for k, v in values.items()}))

    def finish(self) -> None:
        """Close the root frame (idempotent)."""
        if self.root.end is None:
            self.root.end = self.clock()
        self._stack = [self.root]

    def span_durations(self) -> Dict[str, float]:
        """Flat {span name: seconds} over the whole tree (later duplicate
        names accumulate) — the flight recorder's per-cycle timing row."""
        out: Dict[str, float] = {}
        now = self.clock()

        def walk(sp: Span) -> None:
            out[sp.name] = out.get(sp.name, 0.0) + sp.duration_s(now)
            for c in sp.children:
                walk(c)

        walk(self.root)
        return out

    def self_durations(self) -> Dict[str, float]:
        """Flat {span name: seconds EXCLUSIVE of child spans} — the
        perf ledger's phase-attribution view: a ``validate`` nested
        inside ``solve:batch`` counts once, so phase sums are disjoint
        slices of the cycle wall. ``span_durations`` keeps the
        inclusive view the flight recorder documents."""
        out: Dict[str, float] = {}
        now = self.clock()

        def walk(sp: Span) -> None:
            d = sp.duration_s(now) - sum(
                c.duration_s(now) for c in sp.children)
            out[sp.name] = out.get(sp.name, 0.0) + max(d, 0.0)
            for c in sp.children:
                walk(c)

        walk(self.root)
        return out

    # -- Chrome trace-event export ------------------------------------------

    def to_chrome_events(self, pid: int = 1, tid: int = 1) -> List[dict]:
        """Trace-event JSON records (Chrome trace format, "X" complete
        events in microseconds; steps become "i" instant events). ts
        rides the trace's own clock so events from one process line up
        across cycles."""
        self.finish()
        events: List[dict] = []
        # a span leaked open by an exception unwinding past begin_span
        # (deadline timeout mid-solve) still exports with the honest
        # duration-until-trace-end instead of dur=0
        root_end = self.root.end

        def walk(sp: Span) -> None:
            events.append({
                "name": sp.name,
                "ph": "X",
                "pid": pid,
                "tid": tid,
                "ts": round(sp.start * 1e6, 3),
                "dur": round(sp.duration_s(now=root_end) * 1e6, 3),
                **({"args": {k: str(v) for k, v in sp.fields.items()}}
                   if sp.fields else {}),
            })
            for t, msg in sp.steps:
                events.append({
                    "name": msg, "ph": "i", "s": "t",
                    "pid": pid, "tid": tid, "ts": round(t * 1e6, 3),
                })
            for c in sp.children:
                walk(c)

        walk(self.root)
        for t, name, values in self.counters:
            events.append({
                "name": name, "ph": "C", "pid": pid, "tid": tid,
                "ts": round(t * 1e6, 3), "args": values,
            })
        return events


def chrome_trace_json(traces, pid: int = 1) -> dict:
    """The ``chrome://tracing`` / Perfetto file shape: one traceEvents
    list over every given trace (sequential cycles share a tid, so the
    viewer stacks spans by ts/dur containment)."""
    events: List[dict] = []
    for tr in traces:
        events.extend(tr.to_chrome_events(pid=pid))
    return {"traceEvents": events, "displayTimeUnit": "ms"}
