"""Device-memory ledger — HBM accounting, modeled-vs-measured bytes,
capacity preflight, and OOM forensics (the byte-side twin of the perf
ledger, obs/ledger.py).

The perf ledger made *time* falsifiable; until this module *bytes* were
not: the resident NodeTable, the score-cache plane, and the warm
Sinkhorn potentials had no byte accounting, a DeviceOOM was a
fault-injection kind with no forensic story, and nothing could answer
"will this (P, N) shape fit?" before paying for the answer. Three
faces, one :class:`MemoryLedger` facade that
:class:`~kubernetes_tpu.obs.core.Observability` owns:

- **Resident accounting** — every device-resident structure registers
  through the existing cache/warmup seams (the packed NodeTable
  columns, the NodeSummary score cache, the warm potential carry, the
  last pod-batch upload) with MODELED bytes derived from
  shapes x dtypes (:func:`~kubernetes_tpu.obs.jaxtel.tree_nbytes` —
  pure metadata, zero syncs). The MEASURED side samples
  ``device.memory_stats()`` (bytes_in_use / peak_bytes_in_use /
  bytes_limit per device) where the backend provides it, falling back
  to a bounded ``jax.live_arrays()`` census (CPU backends report no
  memory_stats), at cycle boundaries and idle ticks only — never
  inside jit. ``scheduler_device_memory_bytes{kind,device}`` and
  ``scheduler_memory_model_efficiency`` confront the two exactly like
  the perf ledger does for time: -1 sentinel on sample-free cycles,
  stale device series zeroed (the freshness rule).
- **Capacity preflight** — warmup AOT-lowers every bucket; the
  compiled executable's ``memory_analysis()`` (argument / output /
  temp bytes) lands in a per-shape peak table
  (:meth:`record_bucket_memory`) — under the sparsity-first mode the
  restricted (P, C) frame rows join the dense (P, N) buckets — and
  the scheduler preflights each cycle's (P, N, mesh) against
  ``limit x headroom_frac`` (:meth:`preflight`) — splitting the batch
  down to a smaller warmed bucket or shedding it back to the queue
  *instead of* OOMing
  (``scheduler_memory_preflight_total{action=ok|split|shed}``).
- **OOM forensics** — the device-loss/DeviceOOM recovery path calls
  :meth:`record_oom` BEFORE dropping the resident table: a ranked
  ledger snapshot (top residents, watermark history, the cycle's
  shapes and preflight verdict) lands in a bounded forensic ring,
  readable from ``/debug/memory``, the SIGUSR2 debugger dump, and the
  flight recorder's ``mem=`` flag — an OOM becomes an incident record
  instead of a dead process.

Everything runs on the owner's injected clock (graftlint R4-clean) and
is thread-safe: the scheduler thread observes while the
``/debug/memory`` handler thread snapshots."""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.obs.ledger import _dist_summary
from kubernetes_tpu.sanitize import make_lock

#: forensic OOM records retained (each is small; an OOM storm must not
#: grow memory while the process is already memory-sick)
OOM_RING = 16

#: watermark history points retained per ledger (t, measured, modeled)
WATERMARK_RING = 256


def capture_memory_analysis(lower_fn: Callable[[], object]) -> Optional[dict]:
    """Best-effort XLA memory capture: ``lower_fn`` returns a lowered
    jitted computation; its compiled executable's ``memory_analysis()``
    argument/output/temp bytes come back, or None when the backend (or
    this jax version) declines — capture failure must never fail
    warmup. Unlike ``capture_cost_analysis`` there is no lowered-stage
    shortcut: ``memory_analysis`` exists only on the COMPILED stage, so
    this always pays one AOT compile per bucket (host-side, at warmup —
    never on the cycle path)."""
    try:
        ma = lower_fn().compile().memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for key, attr in (("argument_bytes", "argument_size_in_bytes"),
                      ("output_bytes", "output_size_in_bytes"),
                      ("temp_bytes", "temp_size_in_bytes"),
                      ("code_bytes", "generated_code_size_in_bytes"),
                      ("alias_bytes", "alias_size_in_bytes")):
        try:
            out[key] = int(getattr(ma, attr, 0) or 0)
        except Exception:
            out[key] = 0
    # aliased input/output pairs (donated buffers) are counted once:
    # the argument already holds the bytes the output reuses
    total = (out["argument_bytes"] + out["output_bytes"]
             + out["temp_bytes"] - out.get("alias_bytes", 0))
    if total <= 0:
        return None
    out["total_bytes"] = total
    return out


class MemoryLedger:
    """The facade: resident accounting + measured sampling + preflight
    table + forensic ring, one ``observe_cycle`` call per eventful
    cycle from ``Observability.end_cycle`` (zero device syncs), one
    thread-safe ``snapshot`` for ``/debug/memory``."""

    def __init__(self, config=None, metrics=None,
                 clock: Callable[[], float] = time.monotonic,
                 lock_factory=None) -> None:
        if config is None:
            from kubernetes_tpu.config import MemoryLedgerConfig

            config = MemoryLedgerConfig()
        self.config = config
        self.metrics = metrics
        self.clock = clock
        self._lock = make_lock(lock_factory, "obs.memledger")
        #: name -> {"bytes": int, "shape": str, "t": float} — the
        #: modeled resident table (register/deregister through the
        #: cache/warmup seams)
        self._residents: Dict[str, Dict] = {}
        #: (P, N, mesh) -> memory_analysis dict — the warmup-captured
        #: per-bucket peak table the preflight judges against
        self._buckets: Dict[Tuple[int, int, int], Dict[str, int]] = {}
        #: (t, measured_bytes, modeled_bytes) history (bounded)
        self._watermarks: deque = deque(maxlen=WATERMARK_RING)
        #: per-cycle entries: {"cycle", "t", "modeled", "measured",
        #: "efficiency", "preflight"} (bounded by config.history)
        self._entries: deque = deque(
            maxlen=max(1, int(getattr(config, "history", 128))))
        #: forensic OOM records (bounded ring — see record_oom)
        self._ooms: deque = deque(maxlen=OOM_RING)
        #: preflight verdict counts + the last full verdict (forensics)
        self.preflights: Dict[str, int] = {"ok": 0, "split": 0, "shed": 0}
        self._last_preflight: Dict = {}
        #: measured-side state: last sample clock stamp, last per-device
        #: readings, ratcheting peak, last census (arrays, bytes)
        self._last_sample_t = float("-inf")
        self._last_measured: Dict[str, Dict[str, int]] = {}
        self._measured_total = -1  # -1 = never sampled
        self._peak_total = 0
        self._census = (0, 0)
        #: lifetime observed cycles + samples (eviction observable)
        self.observed = 0
        self.samples = 0
        #: (kind, device) gauge series ever exported — stale series
        #: zero (the explain-gauge freshness rule)
        self._series_seen: set = set()

    @property
    def enabled(self) -> bool:
        return bool(getattr(self.config, "enabled", True))

    @property
    def preflight_on(self) -> bool:
        return self.enabled and bool(getattr(self.config, "preflight",
                                             True))

    # -- resident accounting (modeled side) ---------------------------------

    def register(self, name: str, nbytes: int, shape: str = "") -> None:
        """Register (or re-register: last write wins) one
        device-resident structure with its MODELED byte size — callers
        compute it from shapes x dtypes metadata
        (:func:`~kubernetes_tpu.obs.jaxtel.tree_nbytes`), never by
        touching device values."""
        if not self.enabled:
            return
        n = int(nbytes)
        with self._lock:
            if n <= 0:
                self._residents.pop(name, None)
            else:
                self._residents[name] = {"bytes": n, "shape": shape,
                                         "t": self.clock()}

    def register_tree(self, name: str, *trees, shape: str = "") -> None:
        """Register a resident pytree by its metadata byte size."""
        if not self.enabled:
            return
        from kubernetes_tpu.obs.jaxtel import tree_nbytes

        self.register(name, tree_nbytes(*trees), shape=shape)

    def deregister(self, name: str) -> None:
        with self._lock:
            self._residents.pop(name, None)

    def deregister_prefix(self, prefix: str) -> int:
        """Drop every resident whose name starts with ``prefix`` (the
        device-loss path releases a whole family at once); returns how
        many were dropped."""
        with self._lock:
            names = [n for n in self._residents if n.startswith(prefix)]
            for n in names:
                del self._residents[n]
            return len(names)

    def resident_bytes(self) -> int:
        """Total MODELED resident bytes currently registered."""
        with self._lock:
            return sum(r["bytes"] for r in self._residents.values())

    def resident_count(self) -> int:
        with self._lock:
            return len(self._residents)

    def ranked_residents(self, top: int = 0) -> List[Tuple[str, int, str]]:
        """(name, bytes, shape) ranked largest-first (the forensic
        ordering); ``top`` > 0 truncates."""
        with self._lock:
            rows = sorted(
                ((n, r["bytes"], r["shape"])
                 for n, r in self._residents.items()),
                key=lambda x: (-x[1], x[0]))
        return rows[:top] if top else rows

    # -- measured side -------------------------------------------------------

    def census_count(self) -> int:
        with self._lock:
            return self._census[0]

    def _sample_locked(self, now: float) -> None:
        """One measured-side sample: per-device ``memory_stats()``
        where the backend provides it, the bounded live-array census
        otherwise. Host-only metadata reads at the cycle boundary —
        the ledger adds zero syncs inside jit (aval/nbytes metadata
        never forces a device transfer). Caller holds self._lock."""
        measured: Dict[str, Dict[str, int]] = {}
        total = peak = limit = 0
        try:
            import jax

            devices = jax.local_devices()
        except Exception:
            devices = []
        for d in devices:
            try:
                # graftlint: disable=R2 -- declared measured-side
                # boundary: allocator COUNTERS (host metadata), read at
                # the cycle boundary only, never a device value sync
                ms = d.memory_stats()
            except Exception:
                ms = None
            if not ms:
                continue
            row = {"resident": int(ms.get("bytes_in_use", 0) or 0),
                   "peak": int(ms.get("peak_bytes_in_use", 0) or 0),
                   "limit": int(ms.get("bytes_limit", 0) or 0)}
            measured[str(getattr(d, "id", len(measured)))] = row
            total += row["resident"]
            peak += row["peak"]
            limit += row["limit"]
        if not measured:
            # CPU fallback: memory_stats() is None there — walk the
            # live-array census instead, bounded by census_limit so a
            # leak cannot make its own measurement unboundedly slow
            cap = max(int(getattr(self.config, "census_limit", 4096)), 1)
            n = b = 0
            try:
                import jax

                # graftlint: disable=R2 -- declared measured-side
                # boundary: live-array METADATA walk (aval nbytes, no
                # d2h), cycle-boundary only — the CPU stand-in for
                # memory_stats
                for a in jax.live_arrays():
                    if n >= cap:
                        break
                    nb = getattr(a, "nbytes", 0)
                    if nb:
                        n += 1
                        b += int(nb)
            except Exception:
                pass
            self._census = (n, b)
            total = b
            peak = max(self._peak_total, total)
            measured["census"] = {"resident": total, "peak": peak,
                                  "limit": 0}
        self._last_measured = measured
        self._measured_total = total
        self._peak_total = max(self._peak_total, peak, total)
        self._last_sample_t = now
        self.samples += 1
        self._watermarks.append((now, total, sum(
            r["bytes"] for r in self._residents.values())))

    def limit_bytes(self) -> int:
        """The preflight budget's denominator: the configured limit
        when set, else the backend-reported one (summed across
        devices; 0 = unknown — the preflight then never fires)."""
        lim = int(getattr(self.config, "limit_bytes", 0) or 0)
        if lim > 0:
            return lim
        with self._lock:
            return sum(r.get("limit", 0)
                       for r in self._last_measured.values())

    # -- capacity preflight --------------------------------------------------

    def record_bucket_memory(self, P: int, N: int, mesh: int,
                             stats: Optional[dict]) -> None:
        """Land one warmed bucket's AOT ``memory_analysis()`` capture
        in the per-shape peak table (warmup seam; None = the backend
        declined — nothing lands, the preflight stays
        absence-tolerant)."""
        if stats is None or not self.enabled:
            return
        with self._lock:
            self._buckets[(int(P), int(N), int(mesh))] = dict(stats)

    def bucket_table(self) -> Dict[Tuple[int, int, int], Dict[str, int]]:
        with self._lock:
            return dict(self._buckets)

    def preflight(self, P: int, N: int, mesh: int) -> Tuple[str, int, dict]:
        """Judge one cycle's padded (P, N, mesh) against
        ``limit x headroom_frac`` BEFORE the batch is uploaded.
        Returns ``(action, split_P, verdict)``:

        - ``("ok", P, ...)`` — fits, or the ledger cannot judge (no
          warmed capture for this shape, no known limit) — absence
          tolerant by design: an unwarmed shape must not be shed on a
          guess.
        - ``("split", P', ...)`` — over budget, but a smaller warmed
          bucket P' < P fits: the caller trims the batch to P' pods
          and requeues the rest.
        - ``("shed", 0, ...)`` — over budget and no warmed bucket
          fits: the caller requeues the whole batch (APF admission
          sheds upstream; the cycle must not OOM).

        Counts land on ``scheduler_memory_preflight_total{action}``;
        the full verdict is retained for the forensic record."""
        P, N, mesh = int(P), int(N), int(mesh)
        verdict: Dict = {"P": P, "N": N, "mesh": mesh, "action": "ok",
                         "basis": ""}
        action, split_P = "ok", P
        limit = self.limit_bytes()
        frac = min(max(float(getattr(self.config, "headroom_frac", 0.9)),
                       0.0), 1.0)
        budget = int(limit * frac)
        if not self.preflight_on or budget <= 0:
            verdict["basis"] = "no-limit" if self.preflight_on else "off"
        else:
            with self._lock:
                entry = self._buckets.get((P, N, mesh))
                need = entry["total_bytes"] if entry else 0
                verdict.update(budget=budget, need=need)
                if entry is None:
                    verdict["basis"] = "unwarmed"
                elif need <= budget:
                    verdict["basis"] = "fits"
                else:
                    # over budget: the largest warmed smaller pod
                    # bucket at the SAME (N, mesh) that fits wins
                    fit = [p for (p, n, m), e in self._buckets.items()
                           if n == N and m == mesh and p < P
                           and e["total_bytes"] <= budget]
                    if fit:
                        action, split_P = "split", max(fit)
                        verdict["basis"] = "over-budget"
                    else:
                        action, split_P = "shed", 0
                        verdict["basis"] = "over-budget-no-bucket"
        verdict["action"] = action
        verdict["split_P"] = split_P
        with self._lock:
            self.preflights[action] = self.preflights.get(action, 0) + 1
            self._last_preflight = dict(verdict)
        c = getattr(self.metrics, "memory_preflight", None)
        if c is not None:  # duck-typed: metrics fakes stay valid
            c.inc(action=action)
        return action, split_P, verdict

    # -- per-cycle accounting ------------------------------------------------

    def observe_cycle(self, rec=None) -> Optional[dict]:
        """Fold one cycle boundary in: maybe take a measured sample
        (interval-gated on the owner clock), confront modeled resident
        bytes with it, publish the gauges, append the ledger entry.
        Returns the entry dict (None when disabled). ``rec`` is the
        CycleRecord ``end_cycle`` just built (may be None on tick)."""
        if not self.enabled:
            return None
        now = self.clock()
        interval = float(getattr(self.config, "sample_interval_s", 0.0))
        with self._lock:
            sampled = now - self._last_sample_t >= interval
            if sampled:
                self._sample_locked(now)
            modeled = sum(r["bytes"] for r in self._residents.values())
            measured = self._measured_total if sampled else -1
            last = dict(self._last_preflight)
        eff = -1.0
        if measured > 0:
            # clipped like the perf ledger's verdict: a pathological
            # model must not mint absurd gauges
            eff = min(max(float(modeled) / float(measured), 0.0), 8.0)
        entry = {
            "cycle": int(getattr(rec, "cycle", 0) or 0) if rec else 0,
            "t": round(now, 6),
            "modeled_bytes": modeled,
            "measured_bytes": measured,
            "efficiency": round(eff, 4),
            "preflight": last.get("action", ""),
        }
        with self._lock:
            self._entries.append(entry)
            self.observed += 1
        self._publish(modeled, eff)
        return entry

    def tick(self) -> None:
        """Idle-path sample (Scheduler.idle_tick): keep the watermark
        history and the gauges live while no eventful cycle arrives —
        a leak during an idle period must still be visible."""
        if not self.enabled:
            return
        now = self.clock()
        interval = float(getattr(self.config, "sample_interval_s", 0.0))
        with self._lock:
            if now - self._last_sample_t < interval:
                return
            self._sample_locked(now)
            modeled = sum(r["bytes"] for r in self._residents.values())
            measured = self._measured_total
        eff = -1.0
        if measured > 0:
            eff = min(max(float(modeled) / float(measured), 0.0), 8.0)
        self._publish(modeled, eff)

    def _publish(self, modeled: int, eff: float) -> None:
        m = self.metrics
        if m is None:
            return
        g = getattr(m, "device_memory_bytes", None)
        if g is not None:
            with self._lock:
                rows = {d: dict(r) for d, r in self._last_measured.items()}
            live = {("modeled", "all")}
            g.set(float(modeled), kind="modeled", device="all")
            for dev, row in rows.items():
                for kind in ("resident", "peak", "limit"):
                    g.set(float(row.get(kind, 0)), kind=kind, device=dev)
                    live.add((kind, dev))
            # freshness: a device that stops reporting (mesh change,
            # lost shard) zeroes instead of serving its last reading
            for kind, dev in self._series_seen - live:
                g.set(0.0, kind=kind, device=dev)
            self._series_seen |= live
        g_eff = getattr(m, "memory_model_efficiency", None)
        if g_eff is not None:
            g_eff.set(round(eff, 4) if eff >= 0 else -1.0)

    # -- OOM forensics -------------------------------------------------------

    def record_oom(self, site: str, error: str = "", shapes: str = "",
                   cycle: int = 0) -> dict:
        """Capture the ranked forensic record for one DeviceOOM /
        device-loss event — called BEFORE the recovery path drops the
        resident table, so the record shows what was actually resident
        when the device died. Returns the record (also retained in the
        bounded forensic ring for /debug/memory and the debugger)."""
        top = self.ranked_residents(top=8)
        with self._lock:
            watermarks = list(self._watermarks)[-8:]
            last = dict(self._last_preflight)
            measured = self._measured_total
            modeled = sum(r["bytes"] for r in self._residents.values())
        record = {
            "t": round(self.clock(), 6),
            "cycle": int(cycle),
            "site": site,
            "error": str(error)[:200],
            "shapes": shapes,
            "modeled_bytes": modeled,
            "measured_bytes": measured,
            "limit_bytes": self.limit_bytes(),
            "top_residents": [
                {"name": n, "bytes": b, **({"shape": s} if s else {})}
                for n, b, s in top],
            "watermarks": [
                {"t": round(t, 6), "measured": me, "modeled": mo}
                for t, me, mo in watermarks],
            "preflight": last,
        }
        with self._lock:
            self._ooms.append(record)
        return record

    def oom_flag(self, record: dict) -> str:
        """The flight recorder's ``mem=`` flag text for one forensic
        record: site + the top resident — enough to route a postmortem
        to /debug/memory without bloating the record line."""
        top = record.get("top_residents") or []
        head = (f" top={top[0]['name']}:{top[0]['bytes']}B"
                if top else "")
        return f"oom@{record.get('site', '?')}{head}"

    def oom_records(self) -> List[dict]:
        with self._lock:
            return list(self._ooms)

    # -- export --------------------------------------------------------------

    def snapshot(self) -> dict:
        """The /debug/memory body (thread-safe, like /debug/ledger)."""
        with self._lock:
            residents = sorted(
                ({"name": n, **r} for n, r in self._residents.items()),
                key=lambda r: (-r["bytes"], r["name"]))
            modeled = sum(r["bytes"] for r in self._residents.values())
            buckets = {
                f"P{p}xN{n}" + (f"+mesh{m}" if m else ""): dict(e)
                for (p, n, m), e in sorted(self._buckets.items())}
            entries = list(self._entries)
            watermarks = [
                {"t": round(t, 6), "measured": me, "modeled": mo}
                for t, me, mo in self._watermarks]
            out = {
                "enabled": self.enabled,
                "observed": self.observed,
                "samples": self.samples,
                "modeled_bytes": modeled,
                "measured_bytes": self._measured_total,
                "peak_bytes": self._peak_total,
                "census": {"arrays": self._census[0],
                           "bytes": self._census[1]},
                "devices": {d: dict(r)
                            for d, r in self._last_measured.items()},
                "residents": residents,
                "buckets": buckets,
                "preflight": {"counts": dict(self.preflights),
                              "last": dict(self._last_preflight)},
                "watermarks": watermarks,
                "entries": entries,
                "oom_records": list(self._ooms),
            }
        out["limit_bytes"] = self.limit_bytes()
        effs = [e["efficiency"] for e in entries if e["efficiency"] >= 0]
        out["model_efficiency"] = _dist_summary(effs)
        return out

    def arm_summary(self) -> dict:
        """The bench-record shape (``memory`` block per arm;
        scripts/bench_compare.py's ``memory`` gate family reads exactly
        this): modeled-vs-measured resident bytes, efficiency summary,
        watermark vs limit, preflight engagement."""
        with self._lock:
            entries = list(self._entries)
            modeled = sum(r["bytes"] for r in self._residents.values())
            measured = self._measured_total
            peak = self._peak_total
            counts = dict(self.preflights)
            ooms = len(self._ooms)
        effs = [e["efficiency"] for e in entries if e["efficiency"] >= 0]
        return {
            "cycles": len(entries),
            "resident_bytes": {"modeled": modeled,
                               "measured": measured,
                               "peak": peak},
            "model_efficiency": _dist_summary(effs),
            "limit_bytes": self.limit_bytes(),
            "preflight": counts,
            "oom_records": ooms,
        }

    def dump(self) -> str:
        """Readable postmortem text (the SIGUSR2 / debugger.dump
        memory section)."""
        s = self.snapshot()
        lines = [
            f"Memory ledger: modeled={s['modeled_bytes']}B "
            f"measured={s['measured_bytes']}B peak={s['peak_bytes']}B "
            f"limit={s['limit_bytes'] or '-'} "
            f"preflight ok={s['preflight']['counts'].get('ok', 0)} "
            f"split={s['preflight']['counts'].get('split', 0)} "
            f"shed={s['preflight']['counts'].get('shed', 0)}"
        ]
        for r in s["residents"][:8]:
            lines.append(f"  resident {r['name']}: {r['bytes']}B"
                         + (f" {r['shape']}" if r.get("shape") else ""))
        for rec in s["oom_records"]:
            top = ",".join(f"{t['name']}:{t['bytes']}B"
                           for t in rec["top_residents"][:3])
            lines.append(
                f"  OOM @{rec['site']} cycle={rec['cycle']} "
                f"modeled={rec['modeled_bytes']}B "
                f"shapes={rec['shapes'] or '-'} top=[{top}]")
        return "\n".join(lines)
