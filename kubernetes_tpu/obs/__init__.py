"""Observability for the batched solve path.

Three cooperating pieces, all host-side (the hot path stays jit-clean —
every capture happens at the host boundaries graftlint already blesses):

- :mod:`kubernetes_tpu.obs.trace` — the ``k8s.io/utils/trace`` analog
  grown up: nested spans, threshold-gated klog dump, and a Chrome
  trace-event JSON exporter so a scheduling cycle opens in
  ``chrome://tracing`` / Perfetto.
- :mod:`kubernetes_tpu.obs.jaxtel` — runtime JAX telemetry: compile-cache
  hit/miss and retrace-storm counters keyed by call-site + abstract
  shapes (host-side shape digests; zero host syncs inside jitted code),
  plus device<->host transfer accounting at declared host boundaries.
- :mod:`kubernetes_tpu.obs.recorder` — a bounded ring-buffer flight
  recorder of recent cycle records (batch shape digest, ladder tier,
  fallback/retry/breaker transitions, span timings), dumpable via
  debugger.py / SIGUSR2 and the ``/debug/flightrecorder`` endpoint.
- :mod:`kubernetes_tpu.obs.explain` — the batched schedulability
  explainer: one jitted reduction turns the cycle's (P, N) predicate
  failure bitmask into per-pod reason node counts, the cluster-wide
  reason histogram, and one-bit-away relaxations; surfaced on
  ``/debug/why``, the flight recorder, metrics, and ``kubectl``.
- :mod:`kubernetes_tpu.obs.ledger` — the perf ledger: per-cycle
  measured phase-cost distributions confronted with the cost model's
  prediction (``scheduler_cycle_model_efficiency``) plus the
  multi-window SLO burn-rate watchdog; surfaced on ``/debug/ledger``,
  the flight recorder's ``eff=``/``slo=`` flags, and the benches.
- :mod:`kubernetes_tpu.obs.audit` — the state-conservation auditor:
  every pod in exactly one of {queued, assumed, bound, gone}, node
  capacity never exceeded by committed binds, per-audit deltas
  conserving pods; violations land on
  ``scheduler_invariant_violations_total{invariant}``, a spam-filtered
  ``InvariantViolation`` event, and the ``invariants=`` flight flag.

:class:`kubernetes_tpu.obs.core.Observability` is the facade the
scheduler owns; config rides :class:`kubernetes_tpu.config.
ObservabilityConfig` (and its v1alpha1 block).
"""

from kubernetes_tpu.obs.audit import INVARIANTS, StateAuditor, Violation
from kubernetes_tpu.obs.core import Observability
from kubernetes_tpu.obs.explain import (
    ExplainResult,
    PodExplanation,
    UnschedulableReport,
    build_report,
    explain_reduce,
)
from kubernetes_tpu.obs.jaxtel import JaxTelemetry, abstract_digest, tree_nbytes
from kubernetes_tpu.obs.ledger import (
    CycleCostModel,
    LedgerEntry,
    PerfLedger,
    SLOWatchdog,
)
from kubernetes_tpu.obs.recorder import CycleRecord, FlightRecorder
from kubernetes_tpu.obs.trace import (
    DEFAULT_THRESHOLD_S,
    Span,
    Trace,
    chrome_trace_json,
)

__all__ = [
    "INVARIANTS",
    "StateAuditor",
    "Violation",
    "Observability",
    "ExplainResult",
    "PodExplanation",
    "UnschedulableReport",
    "build_report",
    "explain_reduce",
    "JaxTelemetry",
    "abstract_digest",
    "tree_nbytes",
    "CycleCostModel",
    "LedgerEntry",
    "PerfLedger",
    "SLOWatchdog",
    "CycleRecord",
    "FlightRecorder",
    "Span",
    "Trace",
    "DEFAULT_THRESHOLD_S",
    "chrome_trace_json",
]
