"""Flight recorder — a bounded ring buffer of recent scheduling-cycle
records, the black box read AFTER something went wrong.

Metrics aggregate away the shape of an incident; the recorder keeps the
last N cycles verbatim: batch shape digest, which ladder tier actually
produced the placements, every fallback/retry/breaker transition taken,
and the cycle's span timings. Dump paths: ``debugger.dump`` (SIGUSR2),
the ``/debug/flightrecorder`` endpoint on server.py, or
:meth:`FlightRecorder.dump` directly in a postmortem shell.

Capacity is hard-bounded (``collections.deque(maxlen=...)``) so an
incident that lasts hours cannot grow memory — the newest record evicts
the oldest. Timestamps ride the owner's injected clock (monotonic by
default): deterministic under fake clocks, R4-clean."""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from kubernetes_tpu.sanitize import make_lock


@dataclass
class CycleRecord:
    """One cycle's black-box row."""

    cycle: int = 0
    t: float = 0.0  # cycle start, owner clock
    batch_shape: str = ""  # e.g. "P8xN2+topo" (padded pods x nodes)
    tier: str = ""  # ladder tier that produced the placements
    fallbacks: int = 0
    retries: int = 0
    deadline_exceeded: bool = False
    #: (target, old_state, new_state) breaker flips observed this cycle
    breaker_transitions: List[Tuple[str, str, str]] = field(
        default_factory=list)
    attempted: int = 0
    scheduled: int = 0
    unschedulable: int = 0
    elapsed_s: float = 0.0
    #: span name -> seconds (Trace.span_durations of the cycle trace)
    spans: Dict[str, float] = field(default_factory=dict)
    #: JAX telemetry deltas worth keeping per cycle
    retraces: int = 0
    #: d2h bytes this cycle read back across ALL declared sites — the
    #: per-cycle readback budget (docs/perf.md): a healthy steady-state
    #: cycle moves ~KBs (solve-result vector + scalars); a regression to
    #: MB-scale means a full-matrix readback snuck back in
    readback_bytes: int = 0
    sinkhorn_iters: float = -1.0  # -1 = sinkhorn not engaged
    sinkhorn_residual: float = -1.0
    #: top-K unschedulability reasons this cycle — (predicate name,
    #: blocked-pod count) from the explain reduction (obs/explain.py);
    #: empty when nothing failed or the explainer is off
    top_reasons: List[Tuple[str, int]] = field(default_factory=list)
    #: how the cycle's snapshot was produced (full | delta | clean on
    #: the device-resident path, "host" = legacy full pack + upload;
    #: "" = the cycle never reached the snapshot) and how many node
    #: rows were re-packed for it
    snapshot_mode: str = ""
    snapshot_rows: int = 0
    #: which solve the cycle ran ("restricted" = the incremental
    #: candidate-column solve over the cached score plane, "full" =
    #: the cold dense solve; "" = no solve) and what fraction of the
    #: score plane's node columns the cycle REUSED from the cache
    solve_scope: str = ""
    reuse_frac: float = 0.0
    #: sub-batches the pipelined executor ran (0 = monolithic cycle)
    pipeline_chunks: int = 0
    #: what flushed the serving loop's micro-batch window into this
    #: cycle ("bucket-fill" | "max-wait"; "" = not a serving cycle) and
    #: how long the window accumulated before flushing
    flush_trigger: str = ""
    window_s: float = 0.0
    #: recovery provenance: this is the first cycle after a takeover /
    #: cold-start reconciliation (elector epoch when known, else 1)
    takeover: int = 0
    #: resident device snapshot drops + rebuilds this cycle (device
    #: lost / OOM recovery)
    device_resets: int = 0
    #: binds aborted by the lease fence this cycle (deposed leader)
    fenced_binds: int = 0
    #: state-conservation auditor violations stamped onto this cycle
    #: (obs/audit.py; nonzero is a correctness bug, never noise)
    invariant_violations: int = 0
    #: bind RPCs that timed out ambiguously this cycle and went through
    #: the read-your-write resolution protocol
    ambiguous_binds: int = 0
    #: lock-sanitizer findings (order cycles / held-too-long / guard
    #: violations, kubernetes_tpu/sanitize.py) first observed during
    #: this cycle — nonzero marks the cycle eventful: a latent deadlock
    #: hazard is black-box material even if nothing else happened
    lock_findings: int = 0
    #: sharded-backend provenance: node-axis mesh device count the
    #: scheduler ran this cycle under (0 = single-device mode)
    mesh: int = 0
    #: scenario-pack placement-quality scores for this cycle (empty =
    #: scenario mode off / quality gated off)
    scenario: Dict[str, float] = field(default_factory=dict)
    #: perf-ledger verdict (obs/ledger.py): the cost model's predicted
    #: solve seconds for this cycle's shape, modeled/measured efficiency
    #: (-1 = model not populated — no solve, or ledger off), and which
    #: model basis produced the prediction (xla-cost | calibrated |
    #: anchor)
    modeled_s: float = -1.0
    model_efficiency: float = -1.0
    model_basis: str = ""
    #: comma-joined SLO objectives burning as of this cycle ("" = ok) —
    #: SIGUSR2 dumps and /debug/flightrecorder show efficiency + SLO
    #: history without scraping metrics
    slo: str = ""
    #: memory-ledger verdict (obs/memledger.py): modeled resident bytes
    #: at this cycle's boundary, the measured-side sample (-1 = the
    #: boundary fell inside the sample interval — no sample), and the
    #: modeled/measured confrontation (-1 = no verdict, same sentinel
    #: rule as model_efficiency above)
    mem_modeled_bytes: int = -1
    mem_measured_bytes: int = -1
    mem_efficiency: float = -1.0
    #: memory preflight verdict for this cycle's shape ("" = preflight
    #: never ran; ok | split | shed)
    preflight: str = ""
    #: OOM forensic flag (memledger.record_oom ran this cycle — the
    #: ``mem=`` dump flag routes the postmortem to /debug/memory)
    oom_forensic: str = ""

    def to_json(self) -> dict:
        return {
            "cycle": self.cycle,
            "t": round(self.t, 6),
            "batch_shape": self.batch_shape,
            "tier": self.tier,
            "fallbacks": self.fallbacks,
            "retries": self.retries,
            "deadline_exceeded": self.deadline_exceeded,
            "breaker_transitions": [list(x) for x in self.breaker_transitions],
            "attempted": self.attempted,
            "scheduled": self.scheduled,
            "unschedulable": self.unschedulable,
            "elapsed_s": round(self.elapsed_s, 6),
            "spans": {k: round(v, 6) for k, v in self.spans.items()},
            "retraces": self.retraces,
            "readback_bytes": self.readback_bytes,
            **({"sinkhorn_iters": self.sinkhorn_iters,
                "sinkhorn_residual": self.sinkhorn_residual}
               if self.sinkhorn_iters >= 0 else {}),
            **({"top_reasons": [list(x) for x in self.top_reasons]}
               if self.top_reasons else {}),
            **({"snapshot": {"mode": self.snapshot_mode,
                             "rows": self.snapshot_rows}}
               if self.snapshot_mode else {}),
            **({"solve_scope": self.solve_scope,
                "reuse_frac": round(self.reuse_frac, 4)}
               if self.solve_scope else {}),
            **({"pipeline_chunks": self.pipeline_chunks}
               if self.pipeline_chunks else {}),
            **({"microbatch": {"trigger": self.flush_trigger,
                               "window_s": round(self.window_s, 6)}}
               if self.flush_trigger else {}),
            **({"takeover": self.takeover} if self.takeover else {}),
            **({"device_resets": self.device_resets}
               if self.device_resets else {}),
            **({"fenced_binds": self.fenced_binds}
               if self.fenced_binds else {}),
            **({"invariant_violations": self.invariant_violations}
               if self.invariant_violations else {}),
            **({"ambiguous_binds": self.ambiguous_binds}
               if self.ambiguous_binds else {}),
            **({"lock_findings": self.lock_findings}
               if self.lock_findings else {}),
            **({"mesh": self.mesh} if self.mesh else {}),
            **({"scenario": dict(self.scenario)} if self.scenario else {}),
            **({"modeled_s": round(self.modeled_s, 6),
                "model_efficiency": round(self.model_efficiency, 4),
                "model_basis": self.model_basis}
               if self.model_efficiency >= 0 else {}),
            **({"slo": self.slo} if self.slo else {}),
            **({"mem": {"modeled_bytes": self.mem_modeled_bytes,
                        "measured_bytes": self.mem_measured_bytes,
                        "efficiency": round(self.mem_efficiency, 4)}}
               if self.mem_modeled_bytes >= 0 else {}),
            **({"preflight": self.preflight} if self.preflight else {}),
            **({"oom_forensic": self.oom_forensic}
               if self.oom_forensic else {}),
        }


class FlightRecorder:
    """Bounded ring of :class:`CycleRecord`."""

    def __init__(self, capacity: int = 256, lock_factory=None) -> None:
        self.capacity = max(1, int(capacity))
        self._buf: deque = deque(maxlen=self.capacity)
        #: serializes the scheduler thread's appends against snapshot
        #: reads from the /debug handler thread and the SIGUSR2 dump —
        #: iterating a deque mid-append raises RuntimeError
        self._lock = make_lock(lock_factory, "obs.recorder")
        #: lifetime count (so eviction is observable: recorded - len)
        self.recorded = 0

    def record(self, rec: CycleRecord) -> None:
        with self._lock:
            self._buf.append(rec)
            self.recorded += 1

    def records(self) -> List[CycleRecord]:
        with self._lock:
            return list(self._buf)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()

    def to_json(self) -> dict:
        with self._lock:
            recs = list(self._buf)
            recorded = self.recorded
        return {
            "capacity": self.capacity,
            "recorded": recorded,
            "evicted": max(0, recorded - len(recs)),
            "records": [r.to_json() for r in recs],
        }

    def dump(self) -> str:
        """Readable postmortem text (the SIGUSR2 / debugger.dump shape)."""
        with self._lock:
            recs = list(self._buf)
            recorded = self.recorded
        lines = [
            f"Flight recorder: {len(recs)}/{self.capacity} records "
            f"({max(0, recorded - len(recs))} evicted)"
        ]
        for r in recs:
            flags = []
            if r.deadline_exceeded:
                flags.append("DEADLINE")
            if r.fallbacks:
                flags.append(f"fallbacks={r.fallbacks}")
            if r.retries:
                flags.append(f"retries={r.retries}")
            for tgt, old, new in r.breaker_transitions:
                flags.append(f"breaker[{tgt}]:{old}->{new}")
            if r.top_reasons:
                flags.append("why=" + ",".join(
                    f"{name}:{n}" for name, n in r.top_reasons))
            if r.readback_bytes:
                flags.append(f"d2h={r.readback_bytes}B")
            if r.snapshot_mode:
                flags.append(f"snap={r.snapshot_mode}:{r.snapshot_rows}")
            if r.solve_scope:
                flags.append(
                    f"scope={r.solve_scope}:{r.reuse_frac:.0%}")
            if r.pipeline_chunks:
                flags.append(f"chunks={r.pipeline_chunks}")
            if r.flush_trigger:
                flags.append(
                    f"win={r.flush_trigger}:{r.window_s*1000:.1f}ms")
            if r.takeover:
                flags.append(f"takeover=epoch{r.takeover}")
            if r.device_resets:
                flags.append(f"device_reset={r.device_resets}")
            if r.fenced_binds:
                flags.append(f"fenced={r.fenced_binds}")
            if r.invariant_violations:
                flags.append(f"invariants={r.invariant_violations}")
            if r.ambiguous_binds:
                flags.append(f"ambig={r.ambiguous_binds}")
            if r.lock_findings:
                flags.append(f"lockfind={r.lock_findings}")
            if r.model_efficiency >= 0:
                flags.append(f"eff={r.model_efficiency:.2f}")
            if r.slo:
                flags.append(f"slo={r.slo}")
            if r.oom_forensic:
                flags.append(f"mem={r.oom_forensic}")
            elif r.mem_modeled_bytes >= 0:
                flags.append(
                    f"mem={r.mem_modeled_bytes}B"
                    + (f"/{r.mem_measured_bytes}B"
                       if r.mem_measured_bytes >= 0 else ""))
            if r.preflight and r.preflight != "ok":
                flags.append(f"preflight={r.preflight}")
            spans = " ".join(
                f"{k}={v*1000:.1f}ms" for k, v in sorted(r.spans.items()))
            lines.append(
                f"  cycle {r.cycle} t={r.t:.3f} {r.batch_shape or '-'} "
                f"tier={r.tier or '-'} "
                f"attempted={r.attempted} scheduled={r.scheduled} "
                f"unsched={r.unschedulable} {r.elapsed_s*1000:.1f}ms"
                + (f" [{' '.join(flags)}]" if flags else "")
            )
            if spans:
                lines.append(f"    {spans}")
        return "\n".join(lines)
