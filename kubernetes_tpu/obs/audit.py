"""State-conservation auditor — the invariant checker that turns "never
double-place, never lose a pod" from a test assertion into a runtime
surface.

The scheduler's state machine distributes every pod it knows across a
small set of disjoint states: *queued* (one of the three sub-queues),
*assumed* (capacity held, bind in flight or Permit-parked — the cache's
ASSUMED/EXPIRING states), *bound* (watch-confirmed ADDED), or *gone*
(deleted, or terminal). Every chaos PR so far asserted those invariants
at test time; under NETWORK faults (ambiguous bind timeouts, fuzzed
watch streams, relist storms — PR 15) the failure modes are subtle
enough that production needs the checker running online:

``multi-state``       a pod in a queue AND the cache at once (its
                      capacity would be double-counted, and a queued
                      copy of a bound pod is a double-bind in waiting)
``capacity``          a node over-committed by COMMITTED binds (cache
                      pods' effective requests exceed allocatable cpu /
                      memory / pod count)
``lost-pod``          a pod left every local state with no explaining
                      exit — it was neither bound nor deleted (the
                      conservation rule: per-audit deltas must conserve
                      pods); with hub truth provided, also a truth-
                      pending responsible pod tracked nowhere locally
``double-bind-risk``  (truth mode) a hub-bound pod still sitting in a
                      scheduling queue — the exact prelude of a second
                      bind RPC reaching the hub CAS
``stale-entry``       (truth mode) a cached/queued pod the hub no
                      longer contains

Truth-mode checks use a TWO-STRIKE rule (a violation must persist
across two consecutive audits) because the informer feed is eventually
consistent by design — watch lag alone must never page anyone.

Violations land on ``scheduler_invariant_violations_total{invariant}``,
as a spam-filtered ``InvariantViolation`` event, and as the
``invariants=`` flight-record flag (Observability.note_invariant_
violations). The chaos suites run :meth:`audit` continuously with hub
truth; :class:`~kubernetes_tpu.serving.compose.ServingRuntime` runs the
structural checks at ``observability.audit_interval_s``.

Pure host code: dict walks over the queue/cache surfaces, no device
work, no clocks beyond the owner's.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Set

#: every invariant the auditor can report — the metric label vocabulary
INVARIANTS = ("multi-state", "capacity", "lost-pod",
              "double-bind-risk", "stale-entry")


@dataclass
class Violation:
    """One invariant breach: which invariant, the subject (pod key or
    node name), and a human-readable detail line."""

    invariant: str
    subject: str
    detail: str


class StateAuditor:
    """Continuous invariant checker over a live Scheduler.

    ``audit(sched)`` runs the structural checks (multi-state, capacity,
    truthless conservation); ``audit(sched, truth_pods=...)`` adds the
    hub-truth conservation checks. Attach to a scheduler
    (``sched.attach_auditor(auditor)``) so legitimate exits — watch
    deletes, deletion-timestamp skips, reconcile drops — are reported
    via :meth:`note_gone` and never read as lost pods."""

    def __init__(self, metrics=None, event_sink=None, obs=None,
                 keep: int = 64) -> None:
        self.metrics = metrics
        self.event_sink = event_sink
        self.obs = obs
        self.audits = 0
        self.violations_total = 0
        #: ring of recent violations (postmortem surface)
        self.recent: deque = deque(maxlen=max(1, keep))
        #: keys whose exit from all local states is EXPLAINED (watch
        #: delete, deletion-timestamp skip, reconcile drop) since the
        #: last audit — the conservation rule's "gone" bucket
        self._gone: Set[str] = set()
        #: last audit's local state per key (the conservation baseline)
        self._last_states: Optional[Dict[str, str]] = None
        #: truth-mode two-strike memory: candidate violations seen last
        #: audit, confirmed only if still present this audit
        self._truth_strikes: Set[tuple] = set()

    # -- exit accounting (wired by Scheduler.attach_auditor) ---------------

    def note_gone(self, key: str) -> None:
        """A pod legitimately left the scheduler's state machine
        (deleted by the watch, dropped as terminating, removed by a
        takeover reconcile) — conservation must not count it lost."""
        self._gone.add(key)

    # -- the audit ---------------------------------------------------------

    def _local_states(self, sched) -> Dict[str, List[str]]:
        """key -> list of local states the pod currently occupies.
        Disjointness is the invariant: len > 1 is a violation."""
        states: Dict[str, List[str]] = {}
        pending = sched.queue.pending_pods()
        for sub, pods in pending.items():
            for p in pods:
                states.setdefault(p.key(), []).append(f"queued:{sub}")
        for key, st in sched.cache.pod_states().items():
            states.setdefault(key, []).append(st)
        return states

    def audit(self, sched, truth_pods=None) -> List[Violation]:
        """Run every applicable invariant; record, count, and return the
        violations (empty list = clean)."""
        out: List[Violation] = []
        states = self._local_states(sched)

        # 1. exactly-one-state: queued, assumed, and bound are disjoint
        for key, occ in states.items():
            if len(occ) > 1:
                out.append(Violation(
                    "multi-state", key,
                    f"pod occupies {len(occ)} states at once: "
                    f"{', '.join(sorted(occ))}"))

        # 2. capacity: committed binds never exceed a node's allocatable
        for nd in sched.cache.nodes():
            pods = sched.cache.pods_on(nd.name)
            if not pods:
                continue
            cpu = mem = 0.0
            for p in pods:
                req = (p.effective_requests()
                       if hasattr(p, "effective_requests") else p.requests)
                cpu += req.cpu_milli
                mem += req.memory
            alloc = nd.allocatable
            if (cpu > alloc.cpu_milli + 1e-6 or mem > alloc.memory + 1e-6
                    or len(pods) > alloc.pods):
                out.append(Violation(
                    "capacity", nd.name,
                    f"node over-committed by committed binds: "
                    f"cpu {cpu:.0f}/{alloc.cpu_milli:.0f}m "
                    f"mem {mem / 2**20:.0f}/{alloc.memory / 2**20:.0f}Mi "
                    f"pods {len(pods)}/{alloc.pods}"))

        # 3. conservation (truthless): every key of the previous audit
        # is still in some state, was bound (its exit may be a delete
        # whose event is still in flight... no: bound exits also
        # note_gone via the watch), or left through an explained exit
        if self._last_states is not None:
            for key, occ in self._last_states.items():
                if key in states or key in self._gone:
                    continue
                if any(s == "bound" for s in occ):
                    # a bound pod's only exit is deletion; its watch
                    # DELETE also lands in _gone, but a foreign-owned
                    # removal (node delete sweep) may not — bound exits
                    # are never "lost" in the double-bind sense
                    continue
                out.append(Violation(
                    "lost-pod", key,
                    f"pod left every local state (was {occ}) with no "
                    "bind, delete, or reconcile explaining the exit"))

        # 4/5. truth-mode conservation, two-strike confirmed
        strikes: Set[tuple] = set()
        if truth_pods is not None:
            try:
                from kubernetes_tpu.api.types import is_pod_terminated
            except Exception:  # pragma: no cover - import cycle guard
                def is_pod_terminated(_p):
                    return False
            truth = {p.key(): p for p in truth_pods}
            waiting = {wp.pod.key()
                       for wp in sched.framework.waiting.items()}
            for key, tp in truth.items():
                if is_pod_terminated(tp):
                    continue
                if tp.node_name:
                    if any(s.startswith("queued")
                           for s in states.get(key, ())):
                        strikes.add(("double-bind-risk", key))
                        if ("double-bind-risk", key) in self._truth_strikes:
                            out.append(Violation(
                                "double-bind-risk", key,
                                f"hub-bound pod (-> {tp.node_name}) still "
                                "in a scheduling queue two audits in a "
                                "row — a second bind RPC is imminent"))
                elif sched.responsible_for(tp):
                    # only pods the scheduler PREVIOUSLY tracked count:
                    # a pod the informer never delivered is a stream-
                    # health gap (the stall/relist machinery's job),
                    # not a conservation leak of the state machine. The
                    # strike itself carries the was-tracked memory — the
                    # rolled baseline no longer holds the key by the
                    # confirming audit.
                    was_tracked = (self._last_states is not None
                                   and key in self._last_states)
                    prior = ("lost-pod", key) in self._truth_strikes
                    if (key not in states and key not in waiting
                            and (was_tracked or prior)):
                        strikes.add(("lost-pod", key))
                        if prior:
                            out.append(Violation(
                                "lost-pod", key,
                                "truth-pending responsible pod left "
                                "every local state two audits in a row"))
            for key in states:
                if key not in truth:
                    strikes.add(("stale-entry", key))
                    if ("stale-entry", key) in self._truth_strikes:
                        out.append(Violation(
                            "stale-entry", key,
                            "locally tracked pod the hub no longer "
                            "contains (two audits in a row)"))
            # the two-strike memory rolls ONLY on truth audits: a
            # structural sweep interleaved between them (the serving
            # runtime's truthless 2 Hz pass) skipped every truth check
            # and must not reset a pending strike — "two consecutive
            # audits" means two consecutive audits THAT LOOKED
            self._truth_strikes = strikes

        # roll the baselines AFTER the checks
        self._last_states = {k: list(v) for k, v in states.items()}
        self._gone.clear()
        self.audits += 1
        self._publish(out)
        return out

    def _publish(self, violations: List[Violation]) -> None:
        if not violations:
            return
        self.violations_total += len(violations)
        self.recent.extend(violations)
        for v in violations:
            if self.metrics is not None:
                self.metrics.invariant_violations.inc(invariant=v.invariant)
            if self.event_sink is not None:
                from kubernetes_tpu.events import (
                    REASON_INVARIANT_VIOLATION,
                    ObjectRef,
                )

                ns, _, name = v.subject.partition("/")
                ref = (ObjectRef(name=name, namespace=ns,
                                 involved_kind="Pod") if name
                       else ObjectRef(name=v.subject,
                                      involved_kind="Node"))
                self.event_sink(REASON_INVARIANT_VIOLATION, ref,
                                f"{v.invariant}: {v.detail}")
        if self.obs is not None:
            note = getattr(self.obs, "note_invariant_violations", None)
            if note is not None:
                note(len(violations))

    def report(self) -> dict:
        """Bench/chaos summary block."""
        return {
            "audits": self.audits,
            "invariant_violations": self.violations_total,
            "recent": [
                {"invariant": v.invariant, "subject": v.subject,
                 "detail": v.detail}
                for v in list(self.recent)[-8:]
            ],
        }
