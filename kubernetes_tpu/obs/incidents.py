"""Incident autopsies: one correlated bundle per trigger, not four
uncorrelated /debug endpoints.

When something goes wrong today the evidence is scattered: the flight
recorder has the cycle records, /debug/ledger has the SLO verdict,
/debug/memory has the OOM forensics, the queue gauges have the depth —
and nothing ties them to the SAME moment. The
:class:`IncidentRecorder` watches five trigger seams at every cycle
close (all derived from state the facade already holds — zero new
scheduler seams, zero device syncs):

=======================  ================================================
trigger                  detection (at ``Observability.end_cycle``)
=======================  ================================================
``slo-burn``             the PR-14 SLO watchdog's ``burns_total()``
                         advanced this cycle
``invariant-violation``  the state-conservation auditor stamped
                         violations on the cycle record
``oom``                  a DeviceOOM forensic flag landed on the record
``retrace-storm``        the jaxtel per-site storm counters advanced
``ladder-fallback``      the cycle burned >= ``fallback_burst_threshold``
                         ladder fallbacks
=======================  ================================================

Each non-suppressed trigger captures ONE bundle — the flight-recorder
window around the trigger cycle, the perf-ledger and memory-ledger
snapshots, the queue depths, the slowest in-flight journeys, and the
cycle's top unschedulable reasons, all stamped with the SAME trigger
cycle — onto a bounded ring served at ``/debug/incidents`` and
appended to the SIGUSR2 dump. A per-trigger ``cooldown_cycles``
suppression keeps a sustained burn from flooding the ring with
near-identical bundles.

Optionally (config-gated, default off) an incident arms a
``jax.profiler.start_trace`` capture of the next ``profile_cycles``
cycles into a bounded artifact directory (at most ``max_profiles``
captures per process); ``/debug/profile`` arms the same capture on
demand. The profiler calls are best-effort: any failure to start or
stop is swallowed — profiling is forensics, never a crash vector.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Callable, List, Optional

from kubernetes_tpu.sanitize import make_lock

#: the closed trigger vocabulary (metric label values, bundle tags)
TRIGGERS = ("slo-burn", "invariant-violation", "oom", "retrace-storm",
            "ladder-fallback")


class IncidentRecorder:
    """Bounded incident-bundle ring + the optional profiler capture.

    ``config``: :class:`kubernetes_tpu.config.IncidentsConfig` (duck).
    The evidence sources (``recorder``, ``ledger``, ``memledger``,
    ``jaxtel``, ``journeys``) are attached by the Observability facade
    at construction; ``queue_snapshot`` is duck-attached by the
    Scheduler (a callable returning the pending-counts dict) the same
    way the memory ledger rides the cache."""

    def __init__(self, config=None, metrics=None,
                 clock: Callable[[], float] = time.monotonic,
                 lock_factory=None, recorder=None, ledger=None,
                 memledger=None, jaxtel=None, journeys=None) -> None:
        if config is None:
            from kubernetes_tpu.config import IncidentsConfig

            config = IncidentsConfig()
        self.config = config
        self.metrics = metrics
        self.clock = clock
        self.recorder = recorder
        self.ledger = ledger
        self.memledger = memledger
        self.jaxtel = jaxtel
        self.journeys = journeys
        #: duck-attached by the Scheduler: () -> {queue: depth}
        self.queue_snapshot: Optional[Callable[[], dict]] = None
        self._lock = make_lock(lock_factory, "obs.incidents")
        self._ring: deque = deque(
            maxlen=max(int(getattr(config, "capacity", 16)), 1))
        self.total = 0
        self.by_trigger = {t: 0 for t in TRIGGERS}
        #: trigger -> cycle of its last bundle (cooldown suppression)
        self._last_cycle = {}
        # baselines for the delta-detected triggers
        self._burns_seen = 0
        self._storms_seen = 0
        # -- profiler capture state (all under the lock) --
        self._profile_left = 0     # cycles remaining in a live capture
        self._profile_active = False
        self.profiles_taken = 0
        self.profile_errors = 0

    @property
    def enabled(self) -> bool:
        return bool(getattr(self.config, "enabled", False))

    # -- trigger evaluation (called once per eventful cycle close) ---------

    def _burns_total(self) -> int:
        led = self.ledger
        wd = getattr(led, "watchdog", None) if led is not None else None
        try:
            return int(wd.burns_total()) if wd is not None else 0
        except Exception:
            return 0

    def _storms_total(self) -> int:
        jt = self.jaxtel
        try:
            return int(jt.storm_total()) if jt is not None else 0
        except Exception:
            return 0

    def observe_cycle(self, rec) -> List[dict]:
        """Evaluate every trigger against the just-closed cycle record;
        capture one bundle per non-suppressed trigger. Returns the new
        bundles (tests; callers may ignore)."""
        if not self.enabled or rec is None:
            return []
        fired: List[tuple] = []
        burns = self._burns_total()
        if burns > self._burns_seen:
            fired.append(("slo-burn", f"slo burns +{burns - self._burns_seen}"))
        self._burns_seen = burns
        storms = self._storms_total()
        if storms > self._storms_seen:
            fired.append(("retrace-storm",
                          f"retrace storms +{storms - self._storms_seen}"))
        self._storms_seen = storms
        if getattr(rec, "invariant_violations", 0) > 0:
            fired.append(("invariant-violation",
                          f"violations={rec.invariant_violations}"))
        if getattr(rec, "oom_forensic", ""):
            fired.append(("oom", rec.oom_forensic))
        burst = int(getattr(self.config, "fallback_burst_threshold", 3))
        if burst > 0 and getattr(rec, "fallbacks", 0) >= burst:
            fired.append(("ladder-fallback",
                          f"fallbacks={rec.fallbacks}"))
        out: List[dict] = []
        for trigger, detail in fired:
            b = self._capture(trigger, detail, rec)
            if b is not None:
                out.append(b)
        self._profile_tick()
        return out

    def _capture(self, trigger: str, detail: str, rec) -> Optional[dict]:
        cycle = getattr(rec, "cycle", 0)
        cooldown = int(getattr(self.config, "cooldown_cycles", 64))
        with self._lock:
            last = self._last_cycle.get(trigger)
            if last is not None and cycle - last < cooldown:
                return None
            self._last_cycle[trigger] = cycle
        bundle = self._bundle(trigger, detail, rec)
        with self._lock:
            self._ring.append(bundle)
            self.total += 1
            self.by_trigger[trigger] = self.by_trigger.get(trigger, 0) + 1
        if self.metrics is not None:
            self.metrics.incidents_total.inc(trigger=trigger)
        if int(getattr(self.config, "profile_cycles", 0)) > 0:
            self.arm_profile(int(self.config.profile_cycles),
                             tag=f"{trigger}-c{cycle}")
        return bundle

    def _bundle(self, trigger: str, detail: str, rec) -> dict:
        cycle = getattr(rec, "cycle", 0)
        window = int(getattr(self.config, "flight_window", 16))
        flight = []
        if self.recorder is not None:
            flight = [r.to_json() for r in self.recorder.records()
                      if abs(getattr(r, "cycle", 0) - cycle) <= window]
        led = self.ledger
        ledger_snap = (led.snapshot()
                       if led is not None and getattr(led, "enabled", False)
                       else None)
        mem = self.memledger
        mem_snap = (mem.snapshot()
                    if mem is not None and getattr(mem, "enabled", False)
                    else None)
        queues = None
        if self.queue_snapshot is not None:
            try:
                queues = dict(self.queue_snapshot())
            except Exception:
                queues = None
        jr = self.journeys
        slow = (jr.inflight_slowest(
            int(getattr(self.config, "journeys_k", 4)))
            if jr is not None and getattr(jr, "enabled", False) else [])
        return {
            "trigger": trigger,
            "detail": detail,
            "cycle": cycle,
            "t": round(self.clock(), 6),
            "top_reasons": list(getattr(rec, "top_reasons", ()) or ()),
            "flight_window": flight,
            "ledger": ledger_snap,
            "memory": mem_snap,
            "queues": queues,
            "journeys": slow,
        }

    # -- profiler capture ---------------------------------------------------

    def arm_profile(self, cycles: int, tag: str = "manual") -> bool:
        """Start a ``jax.profiler`` trace of the next ``cycles`` cycle
        closes into ``profile_dir`` (bounded by ``max_profiles`` per
        process). Returns True when a capture actually started."""
        cycles = int(cycles)
        outdir = str(getattr(self.config, "profile_dir", "") or "")
        with self._lock:
            if (cycles <= 0 or not outdir or self._profile_active
                    or self.profiles_taken
                    >= int(getattr(self.config, "max_profiles", 4))):
                return False
            self._profile_active = True
            self._profile_left = cycles
            self.profiles_taken += 1
        try:
            import jax

            path = os.path.join(outdir, f"profile-{tag}")
            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)
            return True
        except Exception:
            with self._lock:
                self._profile_active = False
                self._profile_left = 0
                self.profile_errors += 1
            return False

    def _profile_tick(self) -> None:
        with self._lock:
            if not self._profile_active:
                return
            self._profile_left -= 1
            if self._profile_left > 0:
                return
            self._profile_active = False
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            with self._lock:
                self.profile_errors += 1

    # -- read side ----------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def incidents(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def sizes(self) -> dict:
        with self._lock:
            return {"incident_ring": len(self._ring)}

    def snapshot(self) -> dict:
        """The ``/debug/incidents`` body."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "capacity": self._ring.maxlen,
                "total": self.total,
                "by_trigger": {k: v for k, v in self.by_trigger.items()
                               if v},
                "profiles_taken": self.profiles_taken,
                "profile_active": self._profile_active,
                "profile_errors": self.profile_errors,
                "incidents": list(self._ring),
            }

    def dump(self) -> str:
        """SIGUSR2 debugger section: one line per bundle, newest last."""
        with self._lock:
            rows = list(self._ring)
            total = self.total
        lines = [f"== incident ring ({len(rows)} bundles, "
                 f"{total} total) =="]
        for b in rows:
            lines.append(
                f"c{b['cycle']:>6} t={b['t']:.3f} {b['trigger']}: "
                f"{b['detail']} (flight={len(b['flight_window'])} "
                f"journeys={len(b['journeys'])})")
        return "\n".join(lines)
