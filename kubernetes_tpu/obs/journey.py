"""Per-pod journey tracer: where did this pod's seconds go?

Every observability layer before this one (trace spans, flight
recorder, perf ledger, memory ledger) is CYCLE-scoped: when
``scheduler_e2e_scheduling_duration_seconds`` shows a bad p99 none of
them can say which pod was slow or where its end-to-end latency went —
queue wait vs backoff vs ambiguous-bind parking vs solve. The
:class:`JourneyTracker` closes that gap: a bounded per-pod record fed
from the HOST seams the driver already owns (informer add, sub-queue
enter/exit, per-cycle attempt rows, Permit park, fenced bind,
ambiguous park/resolution, preemption eviction, bind RPC, confirm),
decomposing each bound pod's e2e latency into disjoint phase shares:

======================  =================================================
phase                   the pod was ...
======================  =================================================
``queue-wait``          in activeQ / unschedulableQ waiting for a cycle
``backoff``             serving its per-pod failure backoff window
``solve``               popped into an in-flight cycle (snapshot through
                        device solve through explain)
``bind-rpc``            inside the bind RPC (PreBind through confirm)
``ambiguous``           parked awaiting read-your-write resolution of an
                        ambiguous bind timeout (PR 15)
``permit``              parked on a Permit plugin wait
======================  =================================================

The tracker is pure host bookkeeping over the injected clock — zero
device syncs, no jax import — and every mutation takes one lock built
through the scheduler's lock sanitizer (the /debug/journeys handler
thread reads concurrently).

Retention is deliberately three-tiered so the interesting pods survive
without unbounded growth: ALL pending journeys (capped at
``max_pending``; beyond the cap new pods are counted, not tracked),
the slowest-K completed journeys per rolling ``window_s`` window, and
an unconditional 1-in-N completion sample (``sample_every``) so a
healthy fleet still shows representative timelines, not just its tail.
"""

from __future__ import annotations

import bisect
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.sanitize import make_lock

#: internal pod state -> the phase its elapsed time accrues to. The
#: states mirror the queue's sub-queues plus the driver's park points;
#: phases are the public vocabulary (metric label, /debug shares).
PHASE_OF = {
    "active": "queue-wait",
    "unschedulable": "queue-wait",
    "backoff": "backoff",
    "solving": "solve",
    "binding": "bind-rpc",
    "ambiguous": "ambiguous",
    "permit": "permit",
}

#: the closed phase vocabulary, in display order. Bound pods observe
#: EVERY phase (zeros included) so the histogram's per-phase sample
#: counts stay comparable across phases.
PHASES = ("queue-wait", "backoff", "solve", "bind-rpc", "ambiguous",
          "permit")


class Journey:
    """One pod's life, from informer add to confirm (or deletion).

    Plain attribute bag — the tracker owns all mutation under its
    lock; handlers only ever see :meth:`to_json` copies."""

    __slots__ = ("key", "uid", "created_at", "state", "state_since",
                 "phases", "events", "attempts", "elided", "done",
                 "outcome", "finished_at", "e2e_s")

    def __init__(self, key: str, uid: str, now: float) -> None:
        self.key = key
        self.uid = uid
        self.created_at = now
        self.state = "active"
        self.state_since = now
        self.phases: Dict[str, float] = {}
        self.events: List[tuple] = [(now, "created", "")]
        self.attempts: List[dict] = []
        self.elided = 0          # events dropped beyond max_events
        self.done = False
        self.outcome = ""        # "" | bound | gone
        self.finished_at = 0.0
        self.e2e_s = 0.0

    def to_json(self) -> dict:
        total = sum(self.phases.values())
        return {
            "pod": self.key,
            "uid": self.uid,
            "created_at": round(self.created_at, 6),
            "state": self.state,
            "done": self.done,
            "outcome": self.outcome,
            "e2e_s": round(self.e2e_s, 6),
            "phases_s": {k: round(v, 6)
                         for k, v in sorted(self.phases.items())},
            "phase_share": {k: round(v / total, 4)
                            for k, v in sorted(self.phases.items())}
            if total > 0 else {},
            "attempts": list(self.attempts),
            "events": [{"t": round(t, 6), "event": e, "detail": d}
                       for (t, e, d) in self.events],
            "events_elided": self.elided,
        }


class JourneyTracker:
    """The bounded per-pod journey store + its retention policy.

    ``config``: :class:`kubernetes_tpu.config.JourneysConfig` (duck —
    any object with the same fields; ``None`` uses defaults).
    ``metrics``: a :class:`kubernetes_tpu.metrics.SchedulerMetrics`
    (``pod_journey_phase_seconds`` / ``pod_journeys_total``)."""

    def __init__(self, config=None, metrics=None,
                 clock: Callable[[], float] = time.monotonic,
                 lock_factory=None) -> None:
        if config is None:
            from kubernetes_tpu.config import JourneysConfig

            config = JourneysConfig()
        self.config = config
        self.metrics = metrics
        #: per-phase precomputed-label observe handles — six histogram
        #: observes run per BOUND POD, so the label-key derivation is
        #: hoisted out of the bind path (Histogram.child)
        self._phase_observe = (
            {ph: metrics.pod_journey_phase_seconds.child(phase=ph)
             for ph in PHASES} if metrics is not None else None)
        self.clock = clock
        self._lock = make_lock(lock_factory, "obs.journeys")
        #: pod key -> in-flight Journey (bounded by max_pending)
        self._pending: Dict[str, Journey] = {}
        #: completed retention: the slowest-K within the rolling window
        self._slowest: List[Journey] = []
        #: oldest finished_at retained in _slowest — lets _retain's
        #: hot path prove nothing expired without scanning the list
        self._slowest_oldest = 0.0
        #: unconditional 1-in-N completion sample ring
        self._sampled: deque = deque(
            maxlen=max(int(getattr(config, "slow_k", 8)), 4))
        #: journeys touched by the in-flight cycle — finish_cycle
        #: backfills tier/scope onto exactly these attempt rows
        self._cycle_touched: List[dict] = []
        self.created_total = 0
        self.bound_total = 0
        self.gone_total = 0
        #: pods seen while _pending was at capacity — counted, untracked
        self.dropped_total = 0
        self._completed_seq = 0

    @property
    def enabled(self) -> bool:
        return bool(getattr(self.config, "enabled", False))

    # -- seam notes (queue + scheduler call these) --------------------------

    def note_created(self, key: str, uid: str = "") -> None:
        """Informer add landed the pod in the active queue."""
        if not self.enabled:
            return
        with self._lock:
            if key in self._pending:
                return
            if len(self._pending) >= int(self.config.max_pending):
                self.dropped_total += 1
                return
            self._pending[key] = Journey(key, uid, self.clock())
            self.created_total += 1

    def _event(self, j: Journey, name: str, detail: str,
               now: float) -> None:
        if len(j.events) >= int(self.config.max_events):
            j.elided += 1
            return
        j.events.append((now, name, detail))

    def _transition(self, j: Journey, state: str, now: float) -> None:
        phase = PHASE_OF.get(j.state)
        if phase is not None:
            j.phases[phase] = (j.phases.get(phase, 0.0)
                               + max(now - j.state_since, 0.0))
        j.state = state
        j.state_since = now

    def note_queue(self, key: str, queue: str) -> None:
        """The pod moved between sub-queues (active | backoff |
        unschedulable) — the PR-4 queue residency seam."""
        if not self.enabled:
            return
        state = queue if queue in ("active", "backoff",
                                   "unschedulable") else None
        if state is None:
            return
        with self._lock:
            j = self._pending.get(key)
            if j is None or j.done:
                return
            now = self.clock()
            self._transition(j, state, now)
            self._event(j, "queue", queue, now)

    def note_popped(self, key: str, cycle: int) -> None:
        """pop_batch handed the pod to an in-flight cycle."""
        if not self.enabled:
            return
        with self._lock:
            j = self._pending.get(key)
            if j is None or j.done:
                return
            now = self.clock()
            self._transition(j, "solving", now)
            self._event(j, "popped", f"cycle={cycle}", now)

    def note_attempt_failed(self, key: str, cycle: int,
                            reason: str) -> None:
        """The cycle failed the pod (PreFilter, solver, explain, bind
        error ...). The attempt row's tier/scope are backfilled by
        :meth:`finish_cycle` — they are only known once the cycle
        closes."""
        if not self.enabled:
            return
        with self._lock:
            j = self._pending.get(key)
            if j is None or j.done:
                return
            now = self.clock()
            row = {"cycle": int(cycle), "outcome": "failed",
                   "reason": reason, "tier": "", "scope": ""}
            if len(j.attempts) < int(self.config.max_events):
                j.attempts.append(row)
                self._cycle_touched.append(row)
            self._event(j, "failed", reason, now)

    def note_bind_start(self, key: str) -> None:
        """The bind RPC is about to run (PreBind passed)."""
        if not self.enabled:
            return
        with self._lock:
            j = self._pending.get(key)
            if j is None or j.done:
                return
            now = self.clock()
            self._transition(j, "binding", now)
            self._event(j, "bind-start", "", now)

    def note_permit_park(self, key: str, plugin: str = "") -> None:
        """A Permit plugin parked the pod (WAIT verdict)."""
        if not self.enabled:
            return
        with self._lock:
            j = self._pending.get(key)
            if j is None or j.done:
                return
            now = self.clock()
            self._transition(j, "permit", now)
            self._event(j, "permit-park", plugin, now)

    def note_ambiguous_park(self, key: str, origin: str = "") -> None:
        """An ambiguous bind timeout parked the pod for read-your-write
        resolution (PR 15). ``origin`` distinguishes the in-cycle park
        from the expired-assumption reap park."""
        if not self.enabled:
            return
        with self._lock:
            j = self._pending.get(key)
            if j is None:
                return
            now = self.clock()
            if j.done:
                # a reap-origin park can reopen a journey whose bind
                # already confirmed; keep the event, don't re-time
                self._event(j, "ambiguous-park", origin, now)
                return
            self._transition(j, "ambiguous", now)
            self._event(j, "ambiguous-park", origin, now)

    def note_fenced(self, key: str) -> None:
        """The lease fence aborted this pod's bind."""
        if not self.enabled:
            return
        with self._lock:
            j = self._pending.get(key)
            if j is None or j.done:
                return
            self._event(j, "fenced", "", self.clock())

    def note_evicted(self, key: str, by: str = "") -> None:
        """The pod was chosen as a preemption victim."""
        if not self.enabled:
            return
        with self._lock:
            j = self._pending.get(key)
            if j is None or j.done:
                return
            self._event(j, "evicted", by, self.clock())

    def note_bound(self, key: str, cycle: int = 0) -> None:
        """Bind confirmed — close the journey, observe the phase
        histogram (every phase, zeros included), run retention."""
        if not self.enabled:
            return
        with self._lock:
            j = self._pending.pop(key, None)
            if j is None or j.done:
                return
            now = self.clock()
            self._transition(j, "bound", now)
            self._event(j, "bound", f"cycle={cycle}", now)
            row = {"cycle": int(cycle), "outcome": "bound",
                   "reason": "", "tier": "", "scope": ""}
            if len(j.attempts) < int(self.config.max_events):
                j.attempts.append(row)
                self._cycle_touched.append(row)
            j.done = True
            j.outcome = "bound"
            j.finished_at = now
            j.e2e_s = max(now - j.created_at, 0.0)
            self.bound_total += 1
            self._retain(j, now)
        if self._phase_observe is not None:
            phases = j.phases
            for phase, observe in self._phase_observe.items():
                observe(phases.get(phase, 0.0))
            self.metrics.pod_journeys_total.inc(outcome="bound")

    def note_gone(self, key: str) -> None:
        """The pod left the scheduler's responsibility unbound (watch
        delete, terminating skip, reconcile prune, not-ours
        transition)."""
        if not self.enabled:
            return
        with self._lock:
            j = self._pending.pop(key, None)
            if j is None:
                return
            now = self.clock()
            self._transition(j, "gone", now)
            self._event(j, "gone", "", now)
            j.done = True
            j.outcome = "gone"
            j.finished_at = now
            j.e2e_s = max(now - j.created_at, 0.0)
            self.gone_total += 1
        if self.metrics is not None:
            self.metrics.pod_journeys_total.inc(outcome="gone")

    def finish_cycle(self, cycle: int, tier: str, scope: str) -> None:
        """The cycle closed: backfill the ladder tier and solve scope
        onto every attempt row this cycle touched (both are only known
        after the solve ran)."""
        if not self.enabled:
            return
        with self._lock:
            for row in self._cycle_touched:
                if row["cycle"] == cycle:
                    row["tier"] = tier
                    row["scope"] = scope
            self._cycle_touched = []

    # -- retention ----------------------------------------------------------

    def _retain(self, j: Journey, now: float) -> None:
        # caller holds the lock
        self._completed_seq += 1
        n = int(getattr(self.config, "sample_every", 0))
        if n > 0 and self._completed_seq % n == 0:
            self._sampled.append(j)
        window = float(self.config.window_s)
        k = int(self.config.slow_k)
        slow = self._slowest
        # hot path: the common completion neither beats the slowest-K
        # floor nor expires anything — two comparisons, no rebuild.
        # This runs once per BOUND POD, so the filter+sort below must
        # stay off the contended-cycle bind path.
        if (len(slow) >= k and j.e2e_s <= slow[0].e2e_s
                and now - self._slowest_oldest <= window):
            return
        if now - self._slowest_oldest > window:
            slow = [r for r in slow if now - r.finished_at <= window]
            # eviction below can strand a stale (too-old) oldest; that
            # only costs an extra pass through this branch, never a
            # wrongly-retained entry
            self._slowest_oldest = min(
                (r.finished_at for r in slow), default=now)
        # the list is kept ASCENDING by e2e (slowest last): a
        # qualifying completion is one C-level insort + one head pop,
        # not a Python-keyed sort — under a latency ramp (overload)
        # EVERY completion qualifies, so this runs per bound pod
        bisect.insort(slow, j, key=lambda r: r.e2e_s)
        if len(slow) > k:
            del slow[0]
        self._slowest = slow

    # -- read side ----------------------------------------------------------

    def sizes(self) -> Dict[str, int]:
        """Occupancy for ``Scheduler.state_sizes()`` / the soak
        sentinels: everything here must plateau or drain."""
        with self._lock:
            return {"journey_pending": len(self._pending),
                    "journey_slowest": len(self._slowest),
                    "journey_sampled": len(self._sampled)}

    def inflight_slowest(self, k: int) -> List[dict]:
        """The k in-flight journeys that have been pending longest —
        the incident recorder's 'who is hurting right now' slice."""
        with self._lock:
            now = self.clock()
            rows = sorted(self._pending.values(),
                          key=lambda j: j.created_at)[:max(int(k), 0)]
            out = []
            for j in rows:
                d = j.to_json()
                d["pending_s"] = round(max(now - j.created_at, 0.0), 6)
                out.append(d)
            return out

    def timeline(self, key: str) -> Optional[dict]:
        """Full journey for one pod key (pending first, then the
        completed retention tiers) — the ``/debug/journeys?pod=`` body."""
        with self._lock:
            j = self._pending.get(key)
            if j is None:
                for r in self._slowest:
                    if r.key == key:
                        j = r
                        break
            if j is None:
                for r in self._sampled:
                    if r.key == key:
                        j = r
                        break
            return None if j is None else j.to_json()

    def keys(self) -> List[str]:
        """Every key currently resolvable by :meth:`timeline`."""
        with self._lock:
            seen = dict.fromkeys(self._pending)
            for r in self._slowest:
                seen.setdefault(r.key)
            for r in self._sampled:
                seen.setdefault(r.key)
            return list(seen)

    def snapshot(self) -> dict:
        """The bare ``/debug/journeys`` body: counters + the slowest-K
        completed table + the oldest in-flight rows."""
        with self._lock:
            # stored ascending (insort); presented slowest-first
            slowest = [j.to_json() for j in reversed(self._slowest)]
            pending = len(self._pending)
            counters = {"created": self.created_total,
                        "bound": self.bound_total,
                        "gone": self.gone_total,
                        "dropped": self.dropped_total}
        return {
            "enabled": self.enabled,
            "pending": pending,
            **counters,
            "slowest": slowest,
            "inflight": self.inflight_slowest(
                int(getattr(self.config, "slow_k", 8))),
        }
