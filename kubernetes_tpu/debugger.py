"""Cache debugger — dump + compare, the analog of
``pkg/scheduler/internal/cache/debugger/`` (SIGUSR2 handler: ``dumper.go``
prints the cache, ``comparer.go`` diffs cache/queue state against the
apiserver's). The sim harness uses the comparer as its consistency oracle;
a host shim can wire :func:`install_signal_handler` for the SIGUSR2
behavior."""

from __future__ import annotations

import signal
from typing import Dict, List, Tuple


def dump(scheduler) -> str:
    """dumper.go:40 — a readable snapshot of cached nodes (+ usage),
    assumed pods, and queue depths, plus the flight-recorder ring (the
    postmortem view: which ladder tier served recent cycles, their span
    timings, any fallback/retry/breaker activity)."""
    cache = scheduler.cache
    lines: List[str] = ["Dump of cached NodeInfo:"]
    for nd in cache.nodes():
        pods = cache.pods_on(nd.name)
        cpu = sum(p.requests.cpu_milli for p in pods)
        mem = sum(p.requests.memory for p in pods)
        lines.append(
            f"  node {nd.name}: pods={len(pods)} "
            f"req_cpu={cpu:.0f}m/{nd.allocatable.cpu_milli:.0f}m "
            f"req_mem={mem:.0f}/{nd.allocatable.memory:.0f}"
        )
        for p in pods:
            state = "assumed" if cache.is_assumed(p.key()) else "added"
            lines.append(f"    pod {p.key()} [{state}] prio={p.priority}")
    lines.append("Dump of scheduling queue:")
    for q, depth in scheduler.queue.pending_counts().items():
        lines.append(f"  {q}: {depth}")
    obs = getattr(scheduler, "obs", None)
    if obs is not None:
        lines.append(obs.recorder.dump())
        memledger = getattr(obs, "memledger", None)
        if memledger is not None and memledger.enabled:
            # the device-memory view of the same postmortem: ranked
            # residents, watermarks, preflight verdicts, OOM forensics
            lines.append(memledger.dump())
        incidents = getattr(obs, "incidents", None)
        if incidents is not None and incidents.enabled:
            # the correlated-incident view: one line per captured
            # bundle, pointing the postmortem at /debug/incidents
            lines.append(incidents.dump())
    return "\n".join(lines)


def compare(
    scheduler, truth_pods: Dict[str, str], truth_nodes: List[str]
) -> Tuple[List[str], List[str]]:
    """comparer.go:48 CompareNodes/ComparePods: returns (node_diffs,
    pod_diffs) between the cache and the source of truth. ``truth_pods``
    maps pod key -> bound node name ("" = pending); ``truth_nodes`` lists
    live node names. Assumed-but-not-yet-confirmed pods are cache-only by
    design and NOT reported (the reference compares against the nodeinfo
    snapshot the same way: assumed pods are in both)."""
    cache = scheduler.cache
    cached_nodes = {nd.name for nd in cache.nodes()}
    node_diffs = sorted(cached_nodes ^ set(truth_nodes))

    cached: Dict[str, str] = {}
    for nd in cache.nodes():
        for p in cache.pods_on(nd.name):
            cached[p.key()] = nd.name
    pod_diffs: List[str] = []
    bound_truth = {k: n for k, n in truth_pods.items() if n}
    for key, node in bound_truth.items():
        got = cached.get(key)
        if got is None:
            pod_diffs.append(f"{key}: bound to {node} but missing from cache")
        elif got != node:
            pod_diffs.append(f"{key}: cache says {got}, truth says {node}")
    for key, node in cached.items():
        if key not in bound_truth and not cache.is_assumed(key):
            pod_diffs.append(f"{key}: in cache on {node} but not bound in truth")
    return node_diffs, sorted(pod_diffs)


def install_signal_handler(scheduler, sig=signal.SIGUSR2) -> None:
    """debugger.go:29 — SIGUSR2 prints the dump (via the trace logger)."""
    import logging

    log = logging.getLogger("kubernetes_tpu.debugger")

    def handler(signum, frame):
        log.info(dump(scheduler))

    signal.signal(sig, handler)
