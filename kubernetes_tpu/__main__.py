"""``python -m kubernetes_tpu`` — cmd/kube-scheduler/scheduler.go:33 main."""

import sys

from kubernetes_tpu.cli import main

sys.exit(main())
