"""Device-side array bundles (jit-able pytrees) mirroring the host columnar
tables, padded to power-of-two row buckets so XLA shapes stay stable as the
cluster and pending queue grow/shrink (SURVEY.md §7.3.6 — the bucketing
policy that avoids recompilation storms the way the reference avoids
re-listing via incremental snapshots, cache.go:211)."""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.snapshot import (
    NodeTable,
    PodTable,
    SelectorTables,
    TopologyTables,
    VolumeTables,
)
from kubernetes_tpu.utils.interner import bucket_size


class DeviceNodes(NamedTuple):
    """Padded columnar NodeInfo on device. Rows >= n_valid are padding and
    are marked unschedulable so every predicate rejects them."""

    valid: jnp.ndarray  # (N,) bool
    name_id: jnp.ndarray  # (N,) i32
    allocatable: jnp.ndarray  # (N, R) f32
    requested: jnp.ndarray  # (N, R) f32
    nonzero_req: jnp.ndarray  # (N, 2) f32
    pair_mh: jnp.ndarray  # (N, Up) f32 (f32 so memberships ride the MXU)
    key_mh: jnp.ndarray  # (N, Uk) f32
    key_val: jnp.ndarray  # (N, Uk) f32
    key_num: jnp.ndarray  # (N, Uk) f32 — 1 when label parsed as integer
    taint_hard_mh: jnp.ndarray  # (N, Ut) f32
    taint_soft_mh: jnp.ndarray  # (N, Ut) f32
    port_any_mh: jnp.ndarray  # (N, Upp) f32
    port_wild_mh: jnp.ndarray  # (N, Upp) f32
    port_spec_mh: jnp.ndarray  # (N, Upip) f32
    image_mh: jnp.ndarray  # (N, Ui) f32
    owner_counts: jnp.ndarray  # (N, Uo) f32
    zone_id: jnp.ndarray  # (N,) i32
    zone_valid: jnp.ndarray  # (Z,) bool — static shape = padded zone count
    avoid_mh: jnp.ndarray  # (N, Uu) f32
    ready: jnp.ndarray  # (N,) bool
    network_unavailable: jnp.ndarray  # (N,) bool
    schedulable: jnp.ndarray  # (N,) bool
    mem_pressure: jnp.ndarray  # (N,) bool
    disk_pressure: jnp.ndarray  # (N,) bool
    pid_pressure: jnp.ndarray  # (N,) bool
    topo_pair_id: jnp.ndarray  # (N, K) i32 — -1 = key absent
    matcher_counts: jnp.ndarray  # (N, M) f32
    anti_counts: jnp.ndarray  # (N, Ua) f32
    sym_counts: jnp.ndarray  # (N, Us) f32
    aff_pod_count: jnp.ndarray  # (N,) f32
    vol_any_mh: jnp.ndarray  # (N, Uv) f32
    vol_rw_mh: jnp.ndarray  # (N, Uv) f32
    pd_mh: jnp.ndarray  # (N, Uvd) f32
    pd_limit: jnp.ndarray  # (N, 4) f32
    csi_mh: jnp.ndarray  # (N, Uvc) f32
    csi_limit: jnp.ndarray  # (N, Dc) f32 — +inf = no limit
    has_zone_label: jnp.ndarray  # (N,) bool

    @property
    def n(self) -> int:
        return self.name_id.shape[0]


class DevicePods(NamedTuple):
    valid: jnp.ndarray  # (P,) bool
    req: jnp.ndarray  # (P, R) f32
    nonzero_req: jnp.ndarray  # (P, 2) f32
    selprog_id: jnp.ndarray  # (P,) i32
    prefprog_id: jnp.ndarray  # (P,) i32
    tolset_id: jnp.ndarray  # (P,) i32
    name_req: jnp.ndarray  # (P,) i32
    priority: jnp.ndarray  # (P,) i32
    port_wild_pp: jnp.ndarray  # (P, Upp) f32
    port_spec_pp: jnp.ndarray  # (P, Upp) f32
    port_spec_pip: jnp.ndarray  # (P, Upip) f32
    image_mh: jnp.ndarray  # (P, Ui) f32
    owner_id: jnp.ndarray  # (P,) i32
    owner_uid_id: jnp.ndarray  # (P,) i32
    owner_match_mh: jnp.ndarray  # (P, Uo) f32
    order: jnp.ndarray  # (P,) i32
    matcher_mh: jnp.ndarray  # (P, M) f32
    affprog_id: jnp.ndarray  # (P,) i32
    prefaffprog_id: jnp.ndarray  # (P,) i32
    spread_hard_id: jnp.ndarray  # (P,) i32
    spread_soft_id: jnp.ndarray  # (P,) i32
    self_aff_match: jnp.ndarray  # (P,) bool
    anti_term_mh: jnp.ndarray  # (P, Ua) f32
    sym_term_mh: jnp.ndarray  # (P, Us) f32
    has_aff: jnp.ndarray  # (P,) bool
    vol_any_mh: jnp.ndarray  # (P, Uv) f32
    vol_rw_mh: jnp.ndarray  # (P, Uv) f32
    pd_mh: jnp.ndarray  # (P, Uvd) f32
    csi_mh: jnp.ndarray  # (P, Uvc) f32
    vol_error: jnp.ndarray  # (P,) bool
    limits: jnp.ndarray  # (P, 2) f32 cpu/mem limits

    @property
    def n(self) -> int:
        return self.selprog_id.shape[0]


class DeviceSelectors(NamedTuple):
    """Flattened selector programs + toleration tables. Padded rows carry
    explicit valid masks; AND/OR segment reductions use neutral fills."""

    expr_valid: jnp.ndarray  # (E,) bool
    expr_term: jnp.ndarray  # (E,) i32
    expr_op: jnp.ndarray  # (E,) i32
    expr_pairs_mh: jnp.ndarray  # (E, Up) f32
    expr_key: jnp.ndarray  # (E,) i32
    expr_lit: jnp.ndarray  # (E,) f32
    term_valid: jnp.ndarray  # (T,) bool
    term_prog: jnp.ndarray  # (T,) i32
    p_expr_valid: jnp.ndarray
    p_expr_term: jnp.ndarray
    p_expr_op: jnp.ndarray
    p_expr_pairs_mh: jnp.ndarray
    p_expr_key: jnp.ndarray
    p_expr_lit: jnp.ndarray
    p_term_valid: jnp.ndarray
    p_term_prog: jnp.ndarray
    p_term_weight: jnp.ndarray  # (Tp,) f32
    tol_hard_mh: jnp.ndarray  # (S, Ut) f32
    tol_soft_mh: jnp.ndarray  # (S, Ut) f32
    image_sizes: jnp.ndarray  # (Ui,) f32
    # program-count masks: their STATIC shapes carry the padded program
    # counts into segment reductions (ints in a pytree would be traced).
    prog_valid: jnp.ndarray  # (G,) bool
    p_prog_valid: jnp.ndarray  # (Gp,) bool


class DeviceTopology(NamedTuple):
    """Padded inter-pod-affinity / topology-spread term tables. Row tables
    carry valid masks; padded rows point their ``*_prog`` at the dump
    program (index = padded program count) so segment reductions stay
    neutral. ``*_m_onehot`` matrices turn matcher-id gathers into MXU
    matmuls against the (N, M) / (P, M) count matrices."""

    pair_valid: jnp.ndarray  # (Utp,) bool
    # required (anti)affinity rows
    ra_valid: jnp.ndarray  # (Ta,) bool
    ra_prog: jnp.ndarray  # (Ta,) i32 — pad rows -> Ga (dump)
    ra_key: jnp.ndarray  # (Ta,) i32
    ra_m_onehot: jnp.ndarray  # (Ta, M) f32
    ra_anti: jnp.ndarray  # (Ta,) bool
    ga_valid: jnp.ndarray  # (Ga,) bool
    # preferred rows
    rp_valid: jnp.ndarray
    rp_prog: jnp.ndarray
    rp_key: jnp.ndarray
    rp_m_onehot: jnp.ndarray
    rp_w: jnp.ndarray  # (Tp,) f32 signed, pad 0
    gp_valid: jnp.ndarray  # (Gp,) bool
    # anti-term columns
    at_key: jnp.ndarray  # (Ua,) i32
    at_m_onehot: jnp.ndarray  # (Ua, M) f32
    # sym-term columns
    st_key: jnp.ndarray  # (Us,) i32
    st_m_onehot: jnp.ndarray  # (Us, M) f32
    st_w: jnp.ndarray  # (Us,) f32
    st_hard: jnp.ndarray  # (Us,) f32
    # spread hard
    sh_valid: jnp.ndarray  # (Tsh,) bool
    sh_prog: jnp.ndarray  # (Tsh,) i32 — pad -> Gsh
    sh_key: jnp.ndarray
    sh_m_onehot: jnp.ndarray  # (Tsh, M)
    sh_skew: jnp.ndarray  # (Tsh,) f32
    shp_selprog: jnp.ndarray  # (Gsh,) i32, -1 = unconstrained
    shp_valid: jnp.ndarray  # (Gsh,) bool
    # spread soft
    ss_valid: jnp.ndarray
    ss_prog: jnp.ndarray
    ss_key: jnp.ndarray
    ss_m_onehot: jnp.ndarray
    ssp_selprog: jnp.ndarray
    ssp_valid: jnp.ndarray


class DeviceVolumes(NamedTuple):
    """Volume-constraint tables: universe metadata (token kinds/escapes)
    plus this batch's VolumeZone rows and VolumeBinding CNF clauses."""

    conflict_escape: jnp.ndarray  # (Uv,) f32
    pd_type_onehot: jnp.ndarray  # (Uvd, 4) f32
    csi_driver_onehot: jnp.ndarray  # (Uvc, Dc) f32
    vz_valid: jnp.ndarray  # (Rv,) bool
    vz_pod: jnp.ndarray  # (Rv,) i32 — pad rows -> 0 with valid False
    vz_pairs_mh: jnp.ndarray  # (Rv, Up) f32
    vb_row_valid: jnp.ndarray  # (Rb,) bool
    vb_row_clause: jnp.ndarray  # (Rb,) i32
    vb_row_prog: jnp.ndarray  # (Rb,) i32
    vb_clause_valid: jnp.ndarray  # (Cb,) bool
    vb_clause_pod: jnp.ndarray  # (Cb,) i32
    vb_clause_bound: jnp.ndarray  # (Cb,) bool


def _pad_rows(a: np.ndarray, rows: int, fill=0) -> np.ndarray:
    if a.shape[0] == rows:
        return a
    out = np.full((rows,) + a.shape[1:], fill, a.dtype)
    out[: a.shape[0]] = a
    return out


def nodes_to_device(t: NodeTable, pad_to: int | None = None) -> DeviceNodes:
    n_pad = pad_to or bucket_size(max(t.n, 1))
    valid = np.zeros((n_pad,), bool)
    valid[: t.n] = True
    f32 = lambda a: jnp.asarray(_pad_rows(a.astype(np.float32), n_pad))
    return DeviceNodes(
        valid=jnp.asarray(valid),
        name_id=jnp.asarray(_pad_rows(t.name_id, n_pad, -1)),
        allocatable=f32(t.allocatable),
        requested=f32(t.requested),
        nonzero_req=f32(t.nonzero_req),
        pair_mh=f32(t.pair_mh),
        key_mh=f32(t.key_mh),
        key_val=f32(t.key_val),
        key_num=f32(t.key_num),
        taint_hard_mh=f32(t.taint_hard_mh),
        taint_soft_mh=f32(t.taint_soft_mh),
        port_any_mh=f32(t.port_any_mh),
        port_wild_mh=f32(t.port_wild_mh),
        port_spec_mh=f32(t.port_spec_mh),
        image_mh=f32(t.image_mh),
        owner_counts=f32(t.owner_counts),
        zone_id=jnp.asarray(_pad_rows(t.zone_id, n_pad, -1)),
        zone_valid=jnp.asarray(t.zone_valid),
        avoid_mh=f32(t.avoid_mh),
        ready=jnp.asarray(_pad_rows(t.ready, n_pad, False)),
        network_unavailable=jnp.asarray(_pad_rows(t.network_unavailable, n_pad, True)),
        schedulable=jnp.asarray(_pad_rows(t.schedulable, n_pad, False)),
        mem_pressure=jnp.asarray(_pad_rows(t.mem_pressure, n_pad, True)),
        disk_pressure=jnp.asarray(_pad_rows(t.disk_pressure, n_pad, True)),
        pid_pressure=jnp.asarray(_pad_rows(t.pid_pressure, n_pad, True)),
        topo_pair_id=jnp.asarray(_pad_rows(t.topo_pair_id, n_pad, -1)),
        matcher_counts=f32(t.matcher_counts),
        anti_counts=f32(t.anti_counts),
        sym_counts=f32(t.sym_counts),
        aff_pod_count=f32(t.aff_pod_count),
        vol_any_mh=f32(t.vol_any_mh),
        vol_rw_mh=f32(t.vol_rw_mh),
        pd_mh=f32(t.pd_mh),
        pd_limit=jnp.asarray(_pad_rows(t.pd_limit.astype(np.float32), n_pad, 0.0)),
        csi_mh=f32(t.csi_mh),
        csi_limit=jnp.asarray(
            _pad_rows(t.csi_limit.astype(np.float32), n_pad, np.inf)
        ),
        has_zone_label=jnp.asarray(_pad_rows(t.has_zone_label, n_pad, False)),
    )


def pods_to_device(t: PodTable, pad_to: int | None = None) -> DevicePods:
    p_pad = pad_to or bucket_size(max(t.n, 1))
    valid = np.zeros((p_pad,), bool)
    valid[: t.n] = True
    f32 = lambda a: jnp.asarray(_pad_rows(a.astype(np.float32), p_pad))
    i32 = lambda a, fill=-1: jnp.asarray(_pad_rows(a, p_pad, fill))
    return DevicePods(
        valid=jnp.asarray(valid),
        req=f32(t.req),
        nonzero_req=f32(t.nonzero_req),
        selprog_id=i32(t.selprog_id),
        prefprog_id=i32(t.prefprog_id),
        tolset_id=i32(t.tolset_id),
        name_req=i32(t.name_req),
        priority=i32(t.priority, 0),
        port_wild_pp=f32(t.port_wild_pp),
        port_spec_pp=f32(t.port_spec_pp),
        port_spec_pip=f32(t.port_spec_pip),
        image_mh=f32(t.image_mh),
        owner_id=i32(t.owner_id),
        owner_uid_id=i32(t.owner_uid_id),
        owner_match_mh=f32(t.owner_match_mh),
        order=i32(t.order, -1),
        matcher_mh=f32(t.matcher_mh),
        affprog_id=i32(t.affprog_id),
        prefaffprog_id=i32(t.prefaffprog_id),
        spread_hard_id=i32(t.spread_hard_id),
        spread_soft_id=i32(t.spread_soft_id),
        self_aff_match=jnp.asarray(_pad_rows(t.self_aff_match, p_pad, False)),
        anti_term_mh=f32(t.anti_term_mh),
        sym_term_mh=f32(t.sym_term_mh),
        has_aff=jnp.asarray(_pad_rows(t.has_aff, p_pad, False)),
        vol_any_mh=f32(t.vol_any_mh),
        vol_rw_mh=f32(t.vol_rw_mh),
        pd_mh=f32(t.pd_mh),
        csi_mh=f32(t.csi_mh),
        vol_error=jnp.asarray(_pad_rows(t.vol_error, p_pad, False)),
        limits=f32(t.limits),
    )


#: DeviceNodes fields that are NOT (N,)-row-shaped and therefore must not
#: be row-scattered by the delta patch: ``valid`` is resident state (row
#: membership only changes on full rebuilds), ``zone_valid`` is
#: universe-shaped and is refreshed wholesale from the delta pack.
_NODE_NON_ROW_FIELDS = ("valid", "zone_valid")


@partial(jax.jit, donate_argnums=(0,))
def _scatter_node_rows_donated(resident: DeviceNodes, sub: DeviceNodes,
                               idx: jnp.ndarray) -> DeviceNodes:
    """Patch dirty rows of the resident device NodeTable in place.

    ``sub`` carries the re-packed rows (any padding rows beyond the real
    dirty count point their ``idx`` out of bounds and XLA ``mode="drop"``
    discards them); ``resident`` is donated so XLA aliases the output
    onto the existing buffers — the steady-state cycle never reallocates
    or re-uploads the full table. The caller (SchedulerCache) is the sole
    owner of the resident arrays, which is what makes donation safe."""
    out = {}
    for name in DeviceNodes._fields:
        if name in _NODE_NON_ROW_FIELDS:
            continue
        r = getattr(resident, name)
        s = getattr(sub, name)
        out[name] = r.at[idx].set(s, mode="drop")
    return DeviceNodes(valid=resident.valid, zone_valid=sub.zone_valid,
                       **out)


def scatter_node_rows(resident: DeviceNodes, sub: DeviceNodes,
                      idx: np.ndarray) -> DeviceNodes:
    """Jitted row-scatter entry: ``idx`` (D,) host indices aligned with
    ``sub``'s rows; entries >= resident row count are dropped (padding).
    Returns the patched DeviceNodes; the resident argument's buffers are
    donated and must not be used afterwards."""
    return _scatter_node_rows_donated(resident, sub,
                                      jnp.asarray(idx, jnp.int32))


@jax.jit
def gather_node_rows(nodes: DeviceNodes, idx: jnp.ndarray) -> DeviceNodes:
    """The restricted solve's candidate-column view: gather ``idx``
    (C,) node rows out of the (possibly mesh-resident) table into a
    small (C, ·) DeviceNodes the existing solver kernels run on
    unchanged. Out-of-range indices (the candidate_columns padding
    sentinel == N) fill with zeros — ``valid`` fills False, so padded
    rows reject every predicate exactly like bucket-padding rows do.
    ``zone_valid`` is universe-shaped and passes through whole. The
    output is answer-sized (C ≤ the candidate bucket), so under a mesh
    the implied cross-shard gather moves O(C·R) bytes, never the
    (P, N) plane — the readback-budget contract holds."""
    out = {}
    for name in DeviceNodes._fields:
        a = getattr(nodes, name)
        if name == "zone_valid":
            out[name] = a
            continue
        out[name] = jnp.take(a, idx, axis=0, mode="fill", fill_value=0)
    return DeviceNodes(**out)


@partial(jax.jit, static_argnames=("k", "num_shards", "hint_quota"))
def gather_candidates(summary, dirty_mask: jnp.ndarray,
                      nodes: DeviceNodes, k: int, hint_mask=None,
                      num_shards: int = 1, hint_quota: int = 0):
    """Fused candidate pick + row gather — ONE dispatch for the
    restricted solve's column selection (ops/fused_score.
    candidate_columns composed with :func:`gather_node_rows`; separate
    dispatches measurably tax small-cluster cycles on CPU). Returns
    ``(cand_idx, sub_nodes)``. ``hint_mask`` reserves group-quota
    columns (gang home slices / pack hints) — with ``hint_quota > 0``
    as a reserved split capped at quota slots; ``num_shards > 1`` takes
    the mesh-sharded two-stage pick — per-shard local top-k, then a
    replicated merge of only the (S, k) winner frame, never a dense
    plane (bit-identical to the single-pass pick on any shard
    count)."""
    from kubernetes_tpu.ops.fused_score import candidate_columns

    cand = candidate_columns(summary, dirty_mask, k, hint_mask,
                             num_shards, hint_quota)
    return cand, gather_node_rows(nodes, cand)


@jax.jit
def map_restricted_assignment(assigned_local: jnp.ndarray,
                              cand_idx: jnp.ndarray) -> jnp.ndarray:
    """Candidate-local assignment rows -> global node rows, on device:
    the mapped vector rides the cycle's single solve-result readback so
    the candidate index list itself never crosses the host boundary
    (keeping d2h at the answer-sized ~4 B/pod budget)."""
    safe = jnp.clip(assigned_local, 0, cand_idx.shape[0] - 1)
    return jnp.where(assigned_local >= 0,
                     cand_idx[safe].astype(jnp.int32), jnp.int32(-1))


def selectors_to_device(t: SelectorTables) -> DeviceSelectors:
    def pack(n_e, n_t, e_term, e_op, e_pairs, e_key, e_lit, t_prog, t_w=None):
        e_pad = bucket_size(max(n_e, 1))
        t_pad = bucket_size(max(n_t, 1))
        ev = np.zeros((e_pad,), bool)
        ev[:n_e] = True
        tv = np.zeros((t_pad,), bool)
        tv[:n_t] = True
        out = dict(
            expr_valid=jnp.asarray(ev),
            expr_term=jnp.asarray(_pad_rows(e_term, e_pad, 0)),
            expr_op=jnp.asarray(_pad_rows(e_op, e_pad, 0)),
            expr_pairs_mh=jnp.asarray(_pad_rows(e_pairs.astype(np.float32), e_pad)),
            expr_key=jnp.asarray(_pad_rows(e_key, e_pad, -1)),
            expr_lit=jnp.asarray(_pad_rows(e_lit, e_pad, 0.0)),
            term_valid=jnp.asarray(tv),
            term_prog=jnp.asarray(_pad_rows(t_prog, t_pad, 0)),
        )
        if t_w is not None:
            out["term_weight"] = jnp.asarray(_pad_rows(t_w, t_pad, 0.0))
        return out

    r = pack(t.n_exprs, t.n_terms, t.expr_term, t.expr_op, t.expr_pairs_mh,
             t.expr_key, t.expr_lit, t.term_prog)
    p = pack(t.p_n_exprs, t.p_n_terms, t.p_expr_term, t.p_expr_op,
             t.p_expr_pairs_mh, t.p_expr_key, t.p_expr_lit, t.p_term_prog,
             t.p_term_weight)
    s_pad = bucket_size(max(t.tol_hard_mh.shape[0], 1))
    return DeviceSelectors(
        expr_valid=r["expr_valid"],
        expr_term=r["expr_term"],
        expr_op=r["expr_op"],
        expr_pairs_mh=r["expr_pairs_mh"],
        expr_key=r["expr_key"],
        expr_lit=r["expr_lit"],
        term_valid=r["term_valid"],
        term_prog=r["term_prog"],
        p_expr_valid=p["expr_valid"],
        p_expr_term=p["expr_term"],
        p_expr_op=p["expr_op"],
        p_expr_pairs_mh=p["expr_pairs_mh"],
        p_expr_key=p["expr_key"],
        p_expr_lit=p["expr_lit"],
        p_term_valid=p["term_valid"],
        p_term_prog=p["term_prog"],
        p_term_weight=p["term_weight"],
        tol_hard_mh=jnp.asarray(_pad_rows(t.tol_hard_mh.astype(np.float32), s_pad)),
        tol_soft_mh=jnp.asarray(_pad_rows(t.tol_soft_mh.astype(np.float32), s_pad)),
        image_sizes=jnp.asarray(t.image_sizes),
        prog_valid=jnp.asarray(
            _pad_rows(np.ones((t.n_progs,), bool), bucket_size(max(t.n_progs, 1)), False)
        ),
        p_prog_valid=jnp.asarray(
            _pad_rows(np.ones((t.p_n_progs,), bool), bucket_size(max(t.p_n_progs, 1)), False)
        ),
    )


def volumes_to_device(t: VolumeTables) -> DeviceVolumes:
    from kubernetes_tpu.volumes import N_PD_FILTERS

    def onehot(idx: np.ndarray, width: int) -> jnp.ndarray:
        oh = np.zeros((len(idx), width), np.float32)
        if len(idx):
            oh[np.arange(len(idx)), np.clip(idx, 0, width - 1)] = 1.0
        return jnp.asarray(oh)

    def valid(n: int, rows: int) -> jnp.ndarray:
        v = np.zeros((rows,), bool)
        v[:n] = True
        return jnp.asarray(v)

    Rv = bucket_size(max(t.vz_n_rows, 1), 4)
    Rb = bucket_size(max(t.vb_n_rows, 1), 4)
    Cb = bucket_size(max(t.vb_n_clauses, 1), 4)
    Dc = bucket_size(max(t.n_csi_drivers, 1), 4)
    return DeviceVolumes(
        conflict_escape=jnp.asarray(t.conflict_escape),
        pd_type_onehot=onehot(t.pd_type, N_PD_FILTERS),
        csi_driver_onehot=onehot(t.csi_driver, Dc),
        vz_valid=valid(t.vz_n_rows, Rv),
        vz_pod=jnp.asarray(_pad_rows(t.vz_pod, Rv, 0)),
        vz_pairs_mh=jnp.asarray(_pad_rows(t.vz_pairs_mh.astype(np.float32), Rv)),
        vb_row_valid=valid(t.vb_n_rows, Rb),
        vb_row_clause=jnp.asarray(_pad_rows(t.vb_row_clause, Rb, 0)),
        vb_row_prog=jnp.asarray(_pad_rows(t.vb_row_prog, Rb, 0)),
        vb_clause_valid=valid(t.vb_n_clauses, Cb),
        vb_clause_pod=jnp.asarray(_pad_rows(t.vb_clause_pod, Cb, 0)),
        vb_clause_bound=jnp.asarray(_pad_rows(t.vb_clause_bound, Cb, False)),
    )


def topology_to_device(t: TopologyTables) -> DeviceTopology:
    M = t.n_matchers

    def onehot(m_idx: np.ndarray, rows: int) -> jnp.ndarray:
        # negative ids (padding) get an all-zero row, NOT a clipped alias
        # of matcher 0 — the pm_* matmuls are the only validity gate the
        # at/st tables have
        oh = np.zeros((rows, M), np.float32)
        ok = np.asarray(m_idx) >= 0  # graftlint: disable=R7 -- host pack input, never a device value
        r = np.arange(len(m_idx))[ok]
        if len(r):
            oh[r, np.clip(np.asarray(m_idx)[ok], 0, M - 1)] = 1.0  # graftlint: disable=R7 -- host pack input
        return jnp.asarray(oh)

    def valid(n: int, rows: int) -> jnp.ndarray:
        v = np.zeros((rows,), bool)
        v[:n] = True
        return jnp.asarray(v)

    Ta = bucket_size(max(t.ra_n_rows, 1), 4)
    Ga = bucket_size(max(t.ra_n_progs, 1), 4)
    Tp = bucket_size(max(t.rp_n_rows, 1), 4)
    Gp = bucket_size(max(t.rp_n_progs, 1), 4)
    Tsh = bucket_size(max(t.sh_n_rows, 1), 4)
    Gsh = bucket_size(max(t.sh_n_progs, 1), 4)
    Tss = bucket_size(max(t.ss_n_rows, 1), 4)
    Gss = bucket_size(max(t.ss_n_progs, 1), 4)
    n_pairs_pad = bucket_size(max(t.n_pairs, 1))
    i32 = lambda a, rows, fill: jnp.asarray(_pad_rows(a, rows, fill))
    return DeviceTopology(
        pair_valid=valid(t.n_pairs, n_pairs_pad),
        ra_valid=valid(t.ra_n_rows, Ta),
        ra_prog=i32(t.ra_prog, Ta, Ga),
        ra_key=i32(t.ra_key, Ta, 0),
        ra_m_onehot=onehot(_pad_rows(t.ra_m, Ta, 0), Ta),
        ra_anti=jnp.asarray(_pad_rows(t.ra_anti, Ta, False)),
        ga_valid=valid(t.ra_n_progs, Ga),
        rp_valid=valid(t.rp_n_rows, Tp),
        rp_prog=i32(t.rp_prog, Tp, Gp),
        rp_key=i32(t.rp_key, Tp, 0),
        rp_m_onehot=onehot(_pad_rows(t.rp_m, Tp, 0), Tp),
        rp_w=jnp.asarray(_pad_rows(t.rp_w, Tp, 0.0)),
        gp_valid=valid(t.rp_n_progs, Gp),
        at_key=jnp.asarray(t.at_key),
        at_m_onehot=onehot(t.at_m, t.at_m.shape[0]),
        st_key=jnp.asarray(t.st_key),
        st_m_onehot=onehot(t.st_m, t.st_m.shape[0]),
        st_w=jnp.asarray(t.st_w),
        st_hard=jnp.asarray(t.st_hard),
        sh_valid=valid(t.sh_n_rows, Tsh),
        sh_prog=i32(t.sh_prog, Tsh, Gsh),
        sh_key=i32(t.sh_key, Tsh, 0),
        sh_m_onehot=onehot(_pad_rows(t.sh_m, Tsh, 0), Tsh),
        sh_skew=jnp.asarray(_pad_rows(t.sh_skew, Tsh, 0.0)),
        shp_selprog=i32(t.shp_selprog, Gsh, -1),
        shp_valid=valid(t.sh_n_progs, Gsh),
        ss_valid=valid(t.ss_n_rows, Tss),
        ss_prog=i32(t.ss_prog, Tss, Gss),
        ss_key=i32(t.ss_key, Tss, 0),
        ss_m_onehot=onehot(_pad_rows(t.ss_m, Tss, 0), Tss),
        ssp_selprog=i32(t.ssp_selprog, Gss, -1),
        ssp_valid=valid(t.ss_n_progs, Gss),
    )
