"""Inter-pod affinity and topology-spread kernels — the vectorized form of
the reference's hardest predicates/priorities (SURVEY.md §7.3 #1):

- ``InterPodAffinityMatches`` (predicates.go:1211): required pod
  (anti)affinity of the incoming pod AND the symmetric check that no
  *existing* pod's required anti-affinity forbids the incoming pod
  (``satisfiesExistingPodsAntiAffinity``), including the
  first-pod-of-a-group self-match escape (predicates.go:1437).
- ``EvenPodsSpreadPredicate`` (predicates.go:1720): hard maxSkew
  constraints with the candidate-node minimum from
  ``getTPMapMatchingSpreadConstraints`` (metadata.go:194).
- ``CalculateInterPodAffinityPriority`` (interpod_affinity.go:46) with full
  symmetry (existing pods' hard/soft terms scoring the incoming pod).
- ``CalculateEvenPodsSpreadPriority`` (even_pods_spread.go:86).

Representation: topology *pairs* (key, value) are interned host-side; each
node carries ``topo_pair_id (N, K)`` — its pair per topology key. All counts
the reference stores in ``topologyPairsMaps`` (metadata.go:65) become
segment-sums over the node axis of per-node count matrices
(``matcher_counts``/``anti_counts``/``sym_counts``), which the assignment
loop updates by scatter-add as pods land — so in-batch placements influence
later rounds exactly like the reference's serial cache updates.

Matcher-id gathers are expressed as one-hot matmuls against the (·, M)
count matrices so the heavy lifting rides the MXU; the K-loop is unrolled
(K = padded topology-key count, single digits in practice).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops.arrays import (
    DeviceNodes,
    DevicePods,
    DeviceSelectors,
    DeviceTopology,
)

_INF = 3e38


def _group_counts(topo_pair_id: jnp.ndarray, counts: jnp.ndarray, n_pairs: int) -> jnp.ndarray:
    """G[tp, c] = sum of counts[n, c] over nodes n whose pair set includes
    tp. Output has ``n_pairs + 1`` rows; the last row is a dump for nodes
    lacking a key."""
    K = topo_pair_id.shape[1]
    G = jnp.zeros((n_pairs + 1, counts.shape[1]), jnp.float32)
    for k in range(K):
        idx = jnp.where(topo_pair_id[:, k] >= 0, topo_pair_id[:, k], n_pairs)
        G = G + jax.ops.segment_sum(counts, idx, num_segments=n_pairs + 1)
    return G


def _row_counts(
    G: jnp.ndarray,
    topo_pair_id: jnp.ndarray,
    row_key: jnp.ndarray,
    row_m_onehot: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per term-row t (topology key row_key[t], matcher one-hot row) and node
    n: the matcher count within n's topology group of that key.
    Returns (cnt (T, N), has_key (T, N))."""
    N, K = topo_pair_id.shape
    T = row_key.shape[0]
    n_pairs = G.shape[0] - 1
    cnt = jnp.zeros((T, N), jnp.float32)
    has = jnp.zeros((T, N), bool)
    for k in range(K):
        idx = topo_pair_id[:, k]
        hk = idx >= 0
        Gk = G[jnp.where(hk, idx, n_pairs)]  # (N, C)
        cnt_k = row_m_onehot @ Gk.T  # (T, N) MXU
        rs = (row_key == k)[:, None]
        cnt = jnp.where(rs, jnp.where(hk[None, :], cnt_k, 0.0), cnt)
        has = jnp.where(rs, hk[None, :], has)
    return cnt, has


def _col_gather(Gc: jnp.ndarray, topo_pair_id: jnp.ndarray, col_key: jnp.ndarray) -> jnp.ndarray:
    """(N, C): Gc[topo_pair_id[n, col_key[c]], c]; 0 where the node lacks
    column c's key. Gc is (n_pairs+1, C) with per-column keys."""
    N, K = topo_pair_id.shape
    C = col_key.shape[0]
    n_pairs = Gc.shape[0] - 1
    out = jnp.zeros((N, C), jnp.float32)
    for k in range(K):
        idx = topo_pair_id[:, k]
        hk = (idx >= 0)[:, None]
        Gk = Gc[jnp.where(idx >= 0, idx, n_pairs)]  # (N, C)
        cm = (col_key == k)[None, :]
        out = jnp.where(cm & hk, Gk, out)
    return out


def _has_key_rows(topo_pair_id: jnp.ndarray, row_key: jnp.ndarray) -> jnp.ndarray:
    """(T, N) bool: node has topology key row_key[t]."""
    N, K = topo_pair_id.shape
    has = jnp.zeros((row_key.shape[0], N), bool)
    for k in range(K):
        hk = topo_pair_id[:, k] >= 0
        has = jnp.where((row_key == k)[:, None], hk[None, :], has)
    return has


def _seg_all(flags: jnp.ndarray, seg: jnp.ndarray, num: int) -> jnp.ndarray:
    """Segmented AND with neutral True (flags already neutralized on invalid
    rows by the caller)."""
    return jax.ops.segment_min(flags.astype(jnp.int32), seg, num_segments=num) > 0


def _seg_any(flags: jnp.ndarray, seg: jnp.ndarray, num: int) -> jnp.ndarray:
    return jax.ops.segment_max(flags.astype(jnp.int32), seg, num_segments=num) > 0


def inter_pod_affinity_mask(
    pods: DevicePods, nodes: DeviceNodes, topo: DeviceTopology
) -> jnp.ndarray:
    """(P, N) bool — InterPodAffinityMatches (predicates.go:1211)."""
    P = pods.valid.shape[0]
    N = nodes.valid.shape[0]
    n_pairs = topo.pair_valid.shape[0]
    tpid = nodes.topo_pair_id

    # (a) existing pods' required anti-affinity vs the incoming pod
    # (satisfiesExistingPodsAntiAffinity): node fails when any of its
    # topology pairs holds a pod whose anti-term matches the incoming pod.
    A = _group_counts(tpid, nodes.anti_counts, n_pairs)  # (Utp+1, Ua)
    AG = _col_gather(A, tpid, topo.at_key)  # (N, Ua)
    pm_anti = pods.matcher_mh @ topo.at_m_onehot.T  # (P, Ua) — does p match term a
    ok = (pm_anti @ AG.T) <= 0.5  # (P, N)

    # (b) the incoming pod's own required terms
    G = _group_counts(tpid, nodes.matcher_counts, n_pairs)  # (Utp+1, M)
    cnt, has = _row_counts(G, tpid, topo.ra_key, topo.ra_m_onehot)  # (Ta, N)
    n_progs = topo.ga_valid.shape[0]
    seg = topo.ra_prog  # pad rows -> n_progs (dump)
    num = n_progs + 1

    is_aff = topo.ra_valid & ~topo.ra_anti
    is_anti = topo.ra_valid & topo.ra_anti
    row_hit = has & (cnt > 0.5)

    # The reference merges a pod's term matches into ONE pair map keyed by
    # (topologyKey, value) (metadata.go topologyPairsMaps): term t passes at
    # node n if ANY same-key term of the same program hit n's pair. Replicate
    # by OR-ing row hits within (program, key) groups before the per-term
    # checks.
    K = tpid.shape[1]
    seg2 = seg * K + topo.ra_key  # (prog, key) group id
    num2 = num * K
    aff_pair = _seg_any(row_hit & is_aff[:, None], seg2, num2)  # (num2, N)
    anti_pair = _seg_any(row_hit & is_anti[:, None], seg2, num2)

    # nodeMatchesAllTopologyTerms: every affinity row's (key, value) pair is
    # populated; anti rows are neutral-True here.
    aff_all = _seg_all(
        jnp.where(is_aff[:, None], has & aff_pair[seg2], True), seg, num
    )  # (Ga+1, N)
    # nodeMatchesAnyTopologyTerm for anti rows
    anti_any = _seg_any(
        jnp.where(is_anti[:, None], has & anti_pair[seg2], False), seg, num
    )

    # self-match escape: the merged affinity-pair map is empty (no existing
    # pod matches any affinity term on a keyed node) AND the pod matches its
    # own terms (predicates.go:1437).
    mc_tot = jnp.sum(
        jnp.where(has, (topo.ra_m_onehot @ nodes.matcher_counts.T), 0.0), axis=1
    )  # (Ta,) total matching pods per row over keyed nodes
    prog_empty = _seg_all(jnp.where(is_aff, mc_tot <= 0.5, True), seg, num)  # (Ga+1,)
    prog_has_aff = _seg_any(is_aff, seg, num)  # (Ga+1,)

    gid = jnp.clip(pods.affprog_id, 0, n_progs)  # (P,)
    has_prog = pods.affprog_id >= 0
    aff_ok = (
        ~prog_has_aff[gid][:, None]
        | aff_all[gid]
        | (prog_empty[gid] & pods.self_aff_match)[:, None]
    )  # (P, N)
    anti_ok = ~anti_any[gid]
    ok = ok & jnp.where(has_prog[:, None], aff_ok & anti_ok, True)
    return ok


def _spread_candidates(
    sel_match: jnp.ndarray,  # (Gsel, N) from selector_program_match
    nodes: DeviceNodes,
    prog_selprog: jnp.ndarray,  # (Gs,) i32
    row_prog: jnp.ndarray,  # (T,) i32 (pads -> Gs)
    row_key: jnp.ndarray,  # (T,)
    row_valid: jnp.ndarray,  # (T,)
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per spread program: (cand, keys_ok), both (Gs+1, N).
    ``cand`` = nodes that count toward pair totals/min: pass the pod's node
    selector AND carry every constraint's topology key (metadata.go:232-238).
    ``keys_ok`` = key presence alone (the soft-score eligibility,
    even_pods_spread.go initialize() checks only NodeLabelsMatch)."""
    n_selprogs = sel_match.shape[0]
    Gs = prog_selprog.shape[0]
    sel_ok = jnp.where(
        (prog_selprog >= 0)[:, None],
        sel_match[jnp.clip(prog_selprog, 0, n_selprogs - 1)],
        True,
    )  # (Gs, N)
    has = _has_key_rows(nodes.topo_pair_id, row_key)  # (T, N)
    keys_ok = _seg_all(
        jnp.where(row_valid[:, None], has, True), row_prog, Gs + 1
    ) & nodes.valid[None, :]  # (Gs+1, N)
    cand = keys_ok & jnp.concatenate([sel_ok, jnp.zeros((1, sel_ok.shape[1]), bool)])
    return cand, keys_ok


def _spread_pair_counts(
    nodes: DeviceNodes,
    topo_n_pairs: int,
    cand_row: jnp.ndarray,  # (T, N) candidacy per row
    row_key: jnp.ndarray,
    row_m_onehot: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per row t and pair tp: (matching-pod count, candidate-node count),
    accumulated over candidate nodes only. Returns (C, Pres), both
    (n_pairs+1, T)."""
    tpid = nodes.topo_pair_id
    K = tpid.shape[1]
    mc = row_m_onehot @ nodes.matcher_counts.T  # (T, N) matching pods per node
    vals = jnp.where(cand_row, mc, 0.0)
    pres = cand_row.astype(jnp.float32)
    C = jnp.zeros((topo_n_pairs + 1, row_key.shape[0]), jnp.float32)
    Pres = jnp.zeros_like(C)
    for k in range(K):
        idx = jnp.where(tpid[:, k] >= 0, tpid[:, k], topo_n_pairs)
        colk = (row_key == k)[None, :]
        C = C + jax.ops.segment_sum(
            jnp.where(colk, vals.T, 0.0), idx, num_segments=topo_n_pairs + 1
        )
        Pres = Pres + jax.ops.segment_sum(
            jnp.where(colk, pres.T, 0.0), idx, num_segments=topo_n_pairs + 1
        )
    return C, Pres


def _pair_gather_rows(
    C: jnp.ndarray, tpid: jnp.ndarray, row_key: jnp.ndarray
) -> jnp.ndarray:
    """cnt (T, N): C[topo_pair_id[n, k_t], t]; 0 where key absent."""
    N, K = tpid.shape
    T = row_key.shape[0]
    n_pairs = C.shape[0] - 1
    out = jnp.zeros((T, N), jnp.float32)
    for k in range(K):
        idx = tpid[:, k]
        hk = (idx >= 0)[None, :]
        Ck = C[jnp.where(idx >= 0, idx, n_pairs)].T  # (T, N)
        rs = (row_key == k)[:, None]
        out = jnp.where(rs & hk, Ck, out)
    return out


def even_pods_spread_mask(
    pods: DevicePods,
    nodes: DeviceNodes,
    topo: DeviceTopology,
    sel_match: jnp.ndarray,  # (Gsel, N) required-selector program matches
) -> jnp.ndarray:
    """(P, N) bool — EvenPodsSpreadPredicate (predicates.go:1720):
    matchNum + selfMatch - minMatchNum <= maxSkew per hard constraint."""
    P = pods.valid.shape[0]
    N = nodes.valid.shape[0]
    n_pairs = topo.pair_valid.shape[0]
    tpid = nodes.topo_pair_id
    Gsh = topo.shp_valid.shape[0]

    cand, _ = _spread_candidates(
        sel_match, nodes, topo.shp_selprog, topo.sh_prog, topo.sh_key, topo.sh_valid
    )  # (Gsh+1, N)
    cand_row = cand[topo.sh_prog]  # (Tsh, N)
    C, Pres = _spread_pair_counts(nodes, n_pairs, cand_row, topo.sh_key, topo.sh_m_onehot)
    # min match per row over pairs seen on candidate nodes (metadata.go:285);
    # rows with no candidate pairs keep +INF -> skew check passes.
    minm = jnp.min(
        jnp.where(Pres[:n_pairs] > 0.5, C[:n_pairs], _INF), axis=0
    )  # (Tsh,)
    cntn = _pair_gather_rows(C, tpid, topo.sh_key)  # (Tsh, N)
    has = _has_key_rows(tpid, topo.sh_key)
    thr = jnp.minimum(minm + topo.sh_skew, _INF)  # (Tsh,)
    ok0 = cntn <= thr[:, None] + 0.5  # selfMatch = 0
    ok1 = cntn + 1.0 <= thr[:, None] + 0.5  # selfMatch = 1
    fail0 = topo.sh_valid[:, None] & (~has | ~ok0)  # (Tsh, N)
    d = (topo.sh_valid[:, None] & (~has | ~ok1) & ~fail0).astype(jnp.float32)
    F0 = _seg_any(fail0, topo.sh_prog, Gsh + 1)  # (Gsh+1, N)

    self_m = pods.matcher_mh @ topo.sh_m_onehot.T  # (P, Tsh)
    own_row = pods.spread_hard_id[:, None] == topo.sh_prog[None, :]  # (P, Tsh)
    extra = jnp.where(own_row, self_m, 0.0) @ d  # (P, N)

    gid = jnp.clip(pods.spread_hard_id, 0, Gsh)
    fail = F0[gid] | (extra > 0.5)
    return jnp.where((pods.spread_hard_id >= 0)[:, None], ~fail, True)


def even_pods_spread_score(
    pods: DevicePods,
    nodes: DeviceNodes,
    topo: DeviceTopology,
    sel_match: jnp.ndarray,
    mask: jnp.ndarray,  # (P, N) Filter feasibility (the "filtered nodes")
) -> jnp.ndarray:
    """(P, N) f32 — CalculateEvenPodsSpreadPriority (even_pods_spread.go:86):
    10 * (total - count) / (total - min), over filtered candidate nodes."""
    n_pairs = topo.pair_valid.shape[0]
    tpid = nodes.topo_pair_id
    Gss = topo.ssp_valid.shape[0]

    cand, keys_ok = _spread_candidates(
        sel_match, nodes, topo.ssp_selprog, topo.ss_prog, topo.ss_key, topo.ss_valid
    )  # (Gss+1, N)
    cand_row = cand[topo.ss_prog]
    C, _ = _spread_pair_counts(nodes, n_pairs, cand_row, topo.ss_key, topo.ss_m_onehot)
    cntn = _pair_gather_rows(C, tpid, topo.ss_key)  # (Tss, N)
    # per-program per-node credit: sum of pair counts over its constraints
    # (the node's own pairs only — gather already zeroes missing keys, and
    # candidates have all keys anyway)
    CS = jax.ops.segment_sum(
        jnp.where(topo.ss_valid[:, None], cntn, 0.0), topo.ss_prog,
        num_segments=Gss + 1,
    )  # (Gss+1, N)

    gid = jnp.clip(pods.spread_soft_id, 0, Gss)
    has_prog = pods.spread_soft_id >= 0
    cnt_p = CS[gid]  # (P, N)
    # scoring eligibility: filtered nodes with every topology key present —
    # the selector is NOT re-checked here (initialize() vs processAllNode
    # asymmetry in even_pods_spread.go)
    el = keys_ok[gid] & mask
    total = jnp.sum(jnp.where(el, cnt_p, 0.0), axis=1, keepdims=True)  # (P, 1)
    minc = jnp.min(jnp.where(el, cnt_p, _INF), axis=1, keepdims=True)
    any_el = jnp.any(el, axis=1, keepdims=True)
    diff = total - jnp.where(any_el, minc, 0.0)
    score = jnp.where(
        diff > 0,
        jnp.floor(10.0 * (total - cnt_p) / jnp.maximum(diff, 1e-30) + 1e-5),
        10.0,
    )
    score = jnp.where(el, score, 0.0)
    return jnp.where(has_prog[:, None], score, 0.0)


def _key_onehot(keys: jnp.ndarray, K: int) -> jnp.ndarray:
    """(T, K) f32 one-hot of per-row topology-key indices."""
    return (keys[:, None] == jnp.arange(K)[None, :]).astype(jnp.float32)


def sensitive_keys(pods: DevicePods, topo: DeviceTopology, K: int) -> jnp.ndarray:
    """(P, K) bool: topology keys along which admitting this pod in the same
    round as another pod of the same topology group could violate a required
    anti-affinity or hard-spread constraint (either direction). Used by the
    batch solver to serialize such admissions per topology pair per round —
    the batched analog of the serial loop's implicit ordering
    (scheduler.go:462). Keys of *affinity* terms are excluded: affinity
    counts only grow, so a pass can never be invalidated by same-round
    admissions (the self-match escape is handled separately by
    ``self_escape_active``)."""
    n_progs = topo.ga_valid.shape[0]
    Gsh = topo.shp_valid.shape[0]

    # own required anti-affinity keys, via the pod's program
    anti_rows = (topo.ra_valid & topo.ra_anti).astype(jnp.float32)[:, None] * _key_onehot(
        topo.ra_key, K
    )  # (Ta, K)
    prog_anti = (
        jax.ops.segment_sum(anti_rows, topo.ra_prog, num_segments=n_progs + 1) > 0.5
    )  # (Ga+1, K)
    own_anti = jnp.where(
        (pods.affprog_id >= 0)[:, None],
        prog_anti[jnp.clip(pods.affprog_id, 0, n_progs)],
        False,
    )
    # own hard-spread keys
    sh_rows = topo.sh_valid.astype(jnp.float32)[:, None] * _key_onehot(topo.sh_key, K)
    prog_sh = (
        jax.ops.segment_sum(sh_rows, topo.sh_prog, num_segments=Gsh + 1) > 0.5
    )
    own_sh = jnp.where(
        (pods.spread_hard_id >= 0)[:, None],
        prog_sh[jnp.clip(pods.spread_hard_id, 0, Gsh)],
        False,
    )
    # keys of universe anti-terms whose matcher matches this pod (the pod
    # could break an already-admitted pod's anti constraint)
    pm_anti = pods.matcher_mh @ topo.at_m_onehot.T  # (P, Ua)
    match_anti = (pm_anti @ _key_onehot(topo.at_key, K)) > 0.5
    # keys of hard-spread rows whose selector matches this pod (its landing
    # shifts another pod's skew within the round)
    pm_sh = (pods.matcher_mh @ topo.sh_m_onehot.T) * topo.sh_valid[None, :]
    match_sh = (pm_sh @ _key_onehot(topo.sh_key, K)) > 0.5
    return own_anti | own_sh | match_anti | match_sh


def self_escape_active(
    pods: DevicePods, nodes: DeviceNodes, topo: DeviceTopology
) -> jnp.ndarray:
    """(P,) bool: the pod's required-affinity check is passing via the
    first-pod-of-a-group escape (empty pair map + self match) under the
    CURRENT counts. Two escapees of one program must not be admitted in the
    same round — the second must join the first's topology group."""
    has = _has_key_rows(nodes.topo_pair_id, topo.ra_key)  # (Ta, N)
    mc_tot = jnp.sum(
        jnp.where(has, (topo.ra_m_onehot @ nodes.matcher_counts.T), 0.0), axis=1
    )  # (Ta,)
    n_progs = topo.ga_valid.shape[0]
    seg = topo.ra_prog
    num = n_progs + 1
    is_aff = topo.ra_valid & ~topo.ra_anti
    prog_empty = _seg_all(jnp.where(is_aff, mc_tot <= 0.5, True), seg, num)
    prog_has_aff = _seg_any(is_aff, seg, num)
    gid = jnp.clip(pods.affprog_id, 0, n_progs)
    return (
        (pods.affprog_id >= 0)
        & prog_has_aff[gid]
        & prog_empty[gid]
        & pods.self_aff_match
    )


def inter_pod_affinity_score(
    pods: DevicePods,
    nodes: DeviceNodes,
    topo: DeviceTopology,
    mask: jnp.ndarray,
    hard_pod_affinity_weight: float = 1.0,
) -> jnp.ndarray:
    """(P, N) f32 — CalculateInterPodAffinityPriority (interpod_affinity.go):
    weighted term counts (incoming preferred terms + symmetric existing-pod
    terms), min/max-normalized to 0..10 per pod over feasible nodes."""
    n_pairs = topo.pair_valid.shape[0]
    tpid = nodes.topo_pair_id
    Gp = topo.gp_valid.shape[0]

    # incoming pod's preferred terms: +/-w per matching existing pod in the
    # node's topology group of the term's key
    G = _group_counts(tpid, nodes.matcher_counts, n_pairs)
    cnt, has = _row_counts(G, tpid, topo.rp_key, topo.rp_m_onehot)  # (Tp, N)
    w_cnt = topo.rp_w[:, None] * jnp.where(has, cnt, 0.0)
    S_in = jax.ops.segment_sum(
        jnp.where(topo.rp_valid[:, None], w_cnt, 0.0), topo.rp_prog,
        num_segments=Gp + 1,
    )  # (Gp+1, N)
    gid = jnp.clip(pods.prefaffprog_id, 0, Gp)
    score_in = jnp.where((pods.prefaffprog_id >= 0)[:, None], S_in[gid], 0.0)

    # symmetry: existing pods' hard-affinity (x hardPodAffinityWeight),
    # soft-affinity (+w) and soft-anti-affinity (-w) terms that match the
    # incoming pod, credited to the existing pod's whole topology group
    S = _group_counts(tpid, nodes.sym_counts, n_pairs)  # (Utp+1, Us)
    SG = _col_gather(S, tpid, topo.st_key)  # (N, Us)
    pm_sym = pods.matcher_mh @ topo.st_m_onehot.T  # (P, Us)
    w_eff = topo.st_w + topo.st_hard * hard_pod_affinity_weight  # (Us,)
    score_sym = (pm_sym * w_eff[None, :]) @ SG.T  # (P, N)

    counts = score_in + score_sym
    # "counted" nodes (pm.counts non-nil): pod has (anti)affinity, or the
    # node hosts pods with affinity (interpod_affinity.go:121-127)
    counted = pods.has_aff[:, None] | (nodes.aff_pod_count > 0.5)[None, :]
    el = mask & counted
    mx = jnp.maximum(jnp.max(jnp.where(el, counts, 0.0), axis=1, keepdims=True), 0.0)
    mn = jnp.minimum(jnp.min(jnp.where(el, counts, 0.0), axis=1, keepdims=True), 0.0)
    diff = mx - mn
    score = jnp.where(
        (diff > 0) & counted,
        jnp.floor(10.0 * jnp.maximum(counts - mn, 0.0) / jnp.maximum(diff, 1e-30) + 1e-5),
        0.0,
    )
    return score
