"""Vectorized Filter predicates — the reference's 24 boolean node checks
(``pkg/scheduler/algorithm/predicates/predicates.go``) recast as one fused
(pods x nodes) kernel.

Where the reference runs each predicate per (pod, node) inside a 16-goroutine
fan-out (``generic_scheduler.go:531``) with a fixed evaluation order
(``predicates.go:147`` predicatesOrdering), here every check produces a
(P, N) boolean mask in one shot and failures are recorded as per-predicate
bits so the driver can emit the same failure reasons
(``PredicateFailureReason``) for unschedulable pods.

Set-membership checks deliberately evaluate as f32 matmuls over multihot
matrices (labels/taints/ports) so XLA lowers them to the MXU; counts are
exact in f32 well past any realistic universe size.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops.arrays import (
    DeviceNodes,
    DevicePods,
    DeviceSelectors,
    DeviceTopology,
)
from kubernetes_tpu.snapshot import (
    RES_PODS,
    XOP_EXISTS,
    XOP_GT,
    XOP_IN,
    XOP_LT,
    XOP_NOT_EXISTS,
    XOP_NOT_IN,
)

# Failure-reason bit per predicate, ordered like predicatesOrdering
# (predicates.go:147). Names mirror the reference's registration names
# (predicates.go:54-111) for parity checks.
PREDICATE_BITS = (
    "CheckNodeCondition",        # bit 0
    "CheckNodeUnschedulable",    # bit 1
    "PodToleratesNodeTaints",    # bit 2
    "CheckNodeMemoryPressure",   # bit 3
    "CheckNodeDiskPressure",     # bit 4
    "CheckNodePIDPressure",      # bit 5
    "PodFitsHost",               # bit 6 (part of GeneralPredicates)
    "PodFitsHostPorts",          # bit 7
    "PodMatchNodeSelector",      # bit 8
    "PodFitsResources",          # bit 9
    "MatchInterPodAffinity",     # bit 10
    "EvenPodsSpread",            # bit 11
    "NoDiskConflict",            # bit 12
    "MaxVolumeCount",            # bit 13 (all four in-tree checkers + CSI)
    "NoVolumeZoneConflict",      # bit 14
    "VolumeNodeConflict",        # bit 15 (CheckVolumeBinding, bound PVCs)
    "VolumeBindConflict",        # bit 16 (CheckVolumeBinding, unbound PVCs)
    "VolumeError",               # bit 17 (unresolvable PVC/PV state)
)
BIT = {name: i for i, name in enumerate(PREDICATE_BITS)}

# Human-readable failure text per predicate bit, mirroring the reference's
# error vars (algorithm/predicates/error.go:35-79) so FitError events read
# identically. CheckNodeCondition and PodFitsResources are special-cased in
# :func:`fit_error_message` (split into not-ready/network-unavailable and
# per-resource "Insufficient <res>" counts respectively).
REASON_MESSAGES = {
    "CheckNodeUnschedulable": "node(s) were unschedulable",
    "PodToleratesNodeTaints": "node(s) had taints that the pod didn't tolerate",
    "CheckNodeMemoryPressure": "node(s) had memory pressure",
    "CheckNodeDiskPressure": "node(s) had disk pressure",
    "CheckNodePIDPressure": "node(s) had pid pressure",
    "PodFitsHost": "node(s) didn't match the requested hostname",
    "PodFitsHostPorts": "node(s) didn't have free ports for the requested pod ports",
    "PodMatchNodeSelector": "node(s) didn't match node selector",
    "MatchInterPodAffinity": "node(s) didn't match pod affinity/anti-affinity",
    "EvenPodsSpread": "node(s) didn't match pod topology spread constraints",
    "NoDiskConflict": "node(s) had no available disk",
    "MaxVolumeCount": "node(s) exceed max volume count",
    "NoVolumeZoneConflict": "node(s) had no available volume zone",
    "VolumeNodeConflict": "node(s) had volume node affinity conflict",
    "VolumeBindConflict": "node(s) didn't find available persistent volumes to bind",
    "VolumeError": "node(s) had unresolvable volume state",
}


def fit_error_message(rrow, nvalid, req, free, ready, net_unavail,
                      res_names) -> str:
    """FitError.Error() parity (core/generic_scheduler.go:105-122): build
    "0/N nodes are available: <count> <reason>, ..." with per-reason NODE
    COUNTS (sorted as strings, like sortReasonsHistogram), instead of the
    round-2 bare union of reason names.

    ``rrow`` (N,) int32 reason bits for one pod; ``nvalid`` (N,) node
    validity; ``req`` (R,) the pod's request; ``free`` (N, R) allocatable
    minus final usage; ``ready``/``net_unavail`` (N,) node flags;
    ``res_names`` resource-column names. All numpy, host-side — this runs
    only for unplaced pods.

    Two splits recover reference fidelity lost to bit packing:
    - PodFitsResources → per-resource "Insufficient cpu/memory/..."
      (InsufficientResourceError.GetReason, error.go:111).
    - CheckNodeCondition → "node(s) were not ready" vs "node(s) had
      unavailable network" (error.go:67,:69; a node can contribute both,
      matching CheckNodeConditionPredicate's reasons list,
      predicates.go:1631-1640).
    """
    import numpy as np

    hist: dict = {}
    r = np.asarray(rrow)[nvalid]  # graftlint: disable=R7 -- rows already read back at the declared boundary
    n = int(np.count_nonzero(nvalid))
    for name, b in BIT.items():
        fired = ((r >> b) & 1).astype(bool)
        cnt = int(fired.sum())
        if not cnt:
            continue
        if name == "PodFitsResources":
            fv = free[nvalid]
            # all-zero-request pods fail ONLY on the pod-count cap
            # (resource_fit_mask's pods_only branch; predicates.go:803-809
            # quick-return) — scanning every column would fabricate
            # "Insufficient cpu" counts on overcommitted nodes
            nonzero = any(
                req[ri] > 0 for ri in range(len(res_names))
                if res_names[ri] != "pods"
            )
            cols = (
                range(len(res_names)) if nonzero
                else [res_names.index("pods")]
            )
            for ri in cols:
                c = int((fired & (req[ri] > fv[:, ri] + 1e-6)).sum())
                if c:
                    key = f"Insufficient {res_names[ri]}"
                    hist[key] = hist.get(key, 0) + c
        elif name == "CheckNodeCondition":
            c_nr = int((fired & ~ready[nvalid]).sum())
            c_nu = int((fired & net_unavail[nvalid]).sum())
            if c_nr:
                hist["node(s) were not ready"] = (
                    hist.get("node(s) were not ready", 0) + c_nr
                )
            if c_nu:
                hist["node(s) had unavailable network"] = (
                    hist.get("node(s) had unavailable network", 0) + c_nu
                )
        else:
            msg = REASON_MESSAGES[name]
            hist[msg] = hist.get(msg, 0) + cnt
    parts = sorted(f"{v} {k}" for k, v in hist.items())
    return f"0/{n} nodes are available: {', '.join(parts)}."


def fit_error_message_from_counts(counts_row, insufficient_row, not_ready,
                                  net_unavail, n_valid, req,
                                  res_names) -> str:
    """:func:`fit_error_message` rebuilt from the obs/explain.py device
    reductions instead of the raw (P, N) reasons row — byte-identical
    output (regression-pinned by tests/test_fused_validate.py), with the
    per-node bit matrix never crossing the device boundary.

    ``counts_row`` (B,) per-reason valid-node counts
    (ExplainResult.per_pod[i]); ``insufficient_row`` (R,) the
    per-resource Insufficient counts (ExplainResult.insufficient[i]);
    ``not_ready``/``net_unavail`` the CheckNodeCondition splits;
    ``n_valid`` the valid-node count; ``req`` (R,) the pod's request row
    (host pack table)."""
    hist: dict = {}
    for name, b in BIT.items():
        cnt = int(counts_row[b])
        if not cnt:
            continue
        if name == "PodFitsResources":
            nonzero = any(
                req[ri] > 0 for ri in range(len(res_names))
                if res_names[ri] != "pods"
            )
            cols = (
                range(len(res_names)) if nonzero
                else [res_names.index("pods")]
            )
            for ri in cols:
                c = int(insufficient_row[ri])
                if c:
                    key = f"Insufficient {res_names[ri]}"
                    hist[key] = hist.get(key, 0) + c
        elif name == "CheckNodeCondition":
            c_nr, c_nu = int(not_ready), int(net_unavail)
            if c_nr:
                hist["node(s) were not ready"] = (
                    hist.get("node(s) were not ready", 0) + c_nr
                )
            if c_nu:
                hist["node(s) had unavailable network"] = (
                    hist.get("node(s) had unavailable network", 0) + c_nu
                )
        else:
            msg = REASON_MESSAGES[name]
            hist[msg] = hist.get(msg, 0) + cnt
    parts = sorted(f"{v} {k}" for k, v in hist.items())
    return f"0/{n_valid} nodes are available: {', '.join(parts)}."


def selector_program_match(sel: DeviceSelectors, nodes: DeviceNodes) -> jnp.ndarray:
    """(G, N) bool: does node satisfy required selector program g?

    Program semantics (predicates.go:904 PodMatchNodeSelector →
    v1helper.MatchNodeSelectorTerms): OR over terms, AND over a term's
    expressions. Evaluated as flat expression rows + segment reductions.
    """
    return _program_eval(
        nodes,
        sel.expr_valid, sel.expr_term, sel.expr_op, sel.expr_pairs_mh,
        sel.expr_key, sel.expr_lit, sel.term_valid, sel.term_prog,
        n_progs=sel.prog_valid.shape[0],
        weights=None,
    )


def preferred_program_score(sel: DeviceSelectors, nodes: DeviceNodes) -> jnp.ndarray:
    """(Gp, N) f32: sum of weights of matched preferred terms per node
    (priorities/node_affinity.go CalculateNodeAffinityPriorityMap)."""
    return _program_eval(
        nodes,
        sel.p_expr_valid, sel.p_expr_term, sel.p_expr_op, sel.p_expr_pairs_mh,
        sel.p_expr_key, sel.p_expr_lit, sel.p_term_valid, sel.p_term_prog,
        n_progs=sel.p_prog_valid.shape[0],
        weights=sel.p_term_weight,
    )


def _program_eval(nodes, e_valid, e_term, e_op, e_pairs, e_key, e_lit,
                  t_valid, t_prog, n_progs, weights):
    # (E, N) match per expression
    in_count = e_pairs @ nodes.pair_mh.T  # MXU matmul
    key_idx = jnp.clip(e_key, 0, nodes.key_mh.shape[1] - 1)
    has_key = nodes.key_mh[:, key_idx].T  # (E, N)
    val = nodes.key_val[:, key_idx].T  # (E, N)
    is_num = nodes.key_num[:, key_idx].T > 0  # (E, N)
    lit = e_lit[:, None]
    op = e_op[:, None]
    match = jnp.where(op == XOP_IN, in_count > 0, False)
    match = jnp.where(op == XOP_NOT_IN, in_count == 0, match)
    match = jnp.where(op == XOP_EXISTS, has_key > 0, match)
    match = jnp.where(op == XOP_NOT_EXISTS, has_key == 0, match)
    # Gt/Lt require an integer-parsed label value (reference: int-parse
    # error => predicate failure) — explicit mask, no NaN sentinels (NaN
    # compare semantics are not worth trusting across PJRT backends).
    match = jnp.where(op == XOP_GT, is_num & (val > lit), match)
    match = jnp.where(op == XOP_LT, is_num & (val < lit), match)
    # padded expr rows are neutral for the AND
    match = jnp.where(e_valid[:, None], match, True)

    n_terms = t_valid.shape[0]
    term_match = jax.ops.segment_min(
        match.astype(jnp.int32), e_term, num_segments=n_terms
    )  # empty segment -> int32 max -> clamp
    term_match = jnp.minimum(term_match, 1)
    # a term with no expressions matches vacuously ONLY if it is a real term
    # (reference: empty NodeSelectorTerm matches nothing; but our packer only
    # emits terms with >=1 expr, so vacuous-true is unreachable for real rows)
    term_match = jnp.where(t_valid[:, None], term_match, 0)

    if weights is None:
        prog = jax.ops.segment_max(term_match, t_prog, num_segments=n_progs)
        return prog > 0  # (G, N) bool
    w = jnp.where(t_valid, weights, 0.0)
    return jax.ops.segment_sum(
        term_match.astype(jnp.float32) * w[:, None], t_prog, num_segments=n_progs
    )  # (Gp, N) f32


class FilterResult(NamedTuple):
    mask: jnp.ndarray  # (P, N) bool — feasible
    reasons: jnp.ndarray  # (P, N) int32 — failed-predicate bitmask


def static_predicate_reasons(
    pods: DevicePods,
    nodes: DeviceNodes,
    sel: DeviceSelectors,
):
    """Usage-invariant predicate bits plus the node-selector program match
    table, as ``(reasons (P,N) int32, prog (G,N) bool)``.

    Everything here reads only node fields :func:`nodes_with_usage` never
    replaces — conditions, spec.unschedulable, pressure flags, taints,
    hostname, and label membership — so the assignment round loops hoist
    this once per batch and pass it back via ``run_predicates(hoisted=)``.
    The device twin of the reference's per-cycle predicate-metadata
    precomputation (metadata.go:152 GetMetadata: compute shared state
    once, reuse across every node evaluation in the cycle)."""
    P, N = pods.req.shape[0], nodes.allocatable.shape[0]
    reasons = jnp.zeros((P, N), jnp.int32)

    def nodewise(fail_row, bit):
        # (N,) bool fail → broadcast to all pods
        return jnp.where(fail_row[None, :], jnp.int32(1 << bit), 0)

    # CheckNodeCondition (predicates.go:1625): not-ready or
    # network-unavailable fails all pods. Full condition list parity with
    # v1.16 (predicates.go:1631-1640): only NodeReady and
    # NodeNetworkUnavailable are consulted — the out-of-disk condition no
    # longer exists at this version (no OutOfDisk reference anywhere under
    # pkg/scheduler/); spec.unschedulable is the separate bit below.
    reasons |= nodewise(
        ~nodes.ready | nodes.network_unavailable, BIT["CheckNodeCondition"]
    )
    # CheckNodeUnschedulable (eventhandlers/defaults wiring; spec.unschedulable)
    reasons |= nodewise(~nodes.schedulable, BIT["CheckNodeUnschedulable"])
    # CheckNode{Disk,PID}Pressure fail for every pod (predicates.go:1605,:1615)
    reasons |= nodewise(nodes.disk_pressure, BIT["CheckNodeDiskPressure"])
    reasons |= nodewise(nodes.pid_pressure, BIT["CheckNodePIDPressure"])

    # CheckNodeMemoryPressure (predicates.go:1583): only BestEffort pods
    # (zero requests) are rejected.
    best_effort = jnp.sum(pods.req, axis=1) <= 1.0  # only the pods column (==1)
    mem_fail = best_effort[:, None] & nodes.mem_pressure[None, :]
    reasons |= jnp.where(mem_fail, jnp.int32(1 << BIT["CheckNodeMemoryPressure"]), 0)

    # PodToleratesNodeTaints (predicates.go:1546): any NoSchedule/NoExecute
    # taint not tolerated fails. tolerated-count via matmul.
    tol_idx = jnp.clip(pods.tolset_id, 0, sel.tol_hard_mh.shape[0] - 1)
    tol_rows = jnp.where(
        (pods.tolset_id >= 0)[:, None], sel.tol_hard_mh[tol_idx], 0.0
    )  # (P, Ut)
    hard_count = jnp.sum(nodes.taint_hard_mh, axis=1)  # (N,)
    tolerated = tol_rows @ nodes.taint_hard_mh.T  # (P, N)
    taint_fail = (hard_count[None, :] - tolerated) > 0
    reasons |= jnp.where(taint_fail, jnp.int32(1 << BIT["PodToleratesNodeTaints"]), 0)

    # PodFitsHost (predicates.go:916). name_req: -1 = unconstrained,
    # -2 = pinned to an unknown node (fails everywhere), >=0 = must equal.
    host_fail = (pods.name_req != -1)[:, None] & (
        pods.name_req[:, None] != nodes.name_id[None, :]
    )
    reasons |= jnp.where(host_fail, jnp.int32(1 << BIT["PodFitsHost"]), 0)

    # PodMatchNodeSelector (predicates.go:904) via selector programs
    prog = selector_program_match(sel, nodes)  # (G, N)
    prog_idx = jnp.clip(pods.selprog_id, 0, prog.shape[0] - 1)
    sel_ok = jnp.where((pods.selprog_id >= 0)[:, None], prog[prog_idx], True)
    reasons |= jnp.where(~sel_ok, jnp.int32(1 << BIT["PodMatchNodeSelector"]), 0)
    return reasons, prog


def run_predicates(
    pods: DevicePods,
    nodes: DeviceNodes,
    sel: DeviceSelectors,
    topo: DeviceTopology | None = None,
    vol=None,
    static_reasons: jnp.ndarray | None = None,
    enabled_mask=None,
    hoisted=None,
    no_ports: bool = False,
    no_pod_affinity: bool = False,
    no_spread: bool = False,
) -> FilterResult:
    """The fused Filter pass: all predicates, all (pod, node) pairs.

    Equivalent surface: findNodesThatFit (generic_scheduler.go:460) with the
    default predicate set (algorithmprovider/defaults/defaults.go:40) plus
    feature-gated EvenPodsSpread. ``topo=None`` skips the
    inter-pod-affinity/spread passes and ``vol=None`` (a
    :class:`~kubernetes_tpu.ops.arrays.DeviceVolumes`) the five volume
    predicates — cheaper traces for workloads without such constraints.
    ``enabled_mask`` (int bitmask over PREDICATE_BITS) selects the policy's
    predicate set: disabled predicates' failure bits are cleared before the
    feasibility mask forms (CreateFromConfig semantics, factory.go:356);
    mandatory bits should already be included by the config layer.
    ``hoisted`` takes :func:`static_predicate_reasons` output computed
    once per batch against the BASE nodes; the usage-updated ``nodes``
    passed per round then only feed the dynamic predicates.
    ``no_ports`` (static, from :func:`pods_have_no_ports` on the host
    table) skips the three port-conflict matmuls — exact when no pending
    pod declares host ports, since conflicts would be identically zero.
    """
    if hoisted is None:
        reasons, prog = static_predicate_reasons(pods, nodes, sel)
    else:
        reasons, prog = hoisted

    # PodFitsHostPorts (predicates.go:1084, host_ports.go conflict rules):
    # wildcard-IP pod ports conflict with any same-(proto,port) use; specific
    # -IP ports conflict with wildcard uses of (proto,port) or identical
    # (proto,ip,port) uses. Usage-dependent: bound pods add port rows.
    if not no_ports:
        conflicts = (
            pods.port_wild_pp @ nodes.port_any_mh.T
            + pods.port_spec_pp @ nodes.port_wild_mh.T
            + pods.port_spec_pip @ nodes.port_spec_mh.T
        )
        reasons |= jnp.where(
            conflicts > 0, jnp.int32(1 << BIT["PodFitsHostPorts"]), 0
        )

    if topo is not None:
        from kubernetes_tpu.ops.topology import (
            even_pods_spread_mask,
            inter_pod_affinity_mask,
        )

        # The topology universe (dt) is MONOTONIC over a packer's life —
        # one affinity pod ever seen keeps it non-None forever — so the
        # batch-scoped static gates below matter for long-lived drivers:
        # no_pod_affinity (batch has no (anti)affinity pods AND the
        # node-side anti/sym count matrices are all zero) skips the
        # affinity pass incl. the symmetry filter; no_spread (batch has no
        # topologySpreadConstraints) skips the spread pass. Both exact:
        # with those inputs zero the masks are identically all-true.
        if not no_pod_affinity:
            # MatchInterPodAffinity (predicates.go:1211)
            aff_ok = inter_pod_affinity_mask(pods, nodes, topo)
            reasons |= jnp.where(
                ~aff_ok, jnp.int32(1 << BIT["MatchInterPodAffinity"]), 0
            )
        if not no_spread:
            # EvenPodsSpread (predicates.go:1720)
            spread_ok = even_pods_spread_mask(pods, nodes, topo, prog)
            reasons |= jnp.where(
                ~spread_ok, jnp.int32(1 << BIT["EvenPodsSpread"]), 0
            )

    if vol is not None:
        reasons |= _dynamic_volume_reasons(pods, nodes, vol)
    if static_reasons is not None:
        reasons |= static_reasons

    # PodFitsResources (predicates.go:779): the pod-count cap always applies;
    # the remaining columns are checked only when the pod requests *anything*
    # (predicates.go:803-809: an all-zero request short-circuits), and then
    # every column is checked unconditionally — an overcommitted node fails
    # even for dimensions the pod does not request.
    res_fail = ~resource_fit_mask(pods.req, nodes.allocatable, nodes.requested)
    reasons |= jnp.where(res_fail, jnp.int32(1 << BIT["PodFitsResources"]), 0)

    if enabled_mask is not None:
        reasons &= jnp.int32(enabled_mask)
    # padding: invalid nodes/pods are infeasible with no reasons surfaced
    mask = (reasons == 0) & nodes.valid[None, :] & pods.valid[:, None]
    return FilterResult(mask=mask, reasons=reasons)


def _dynamic_volume_reasons(
    pods: DevicePods, nodes: DeviceNodes, vol
) -> jnp.ndarray:
    """Usage-dependent volume predicates (they read node volume state that
    changes as pods land, so they re-evaluate every assignment round):

    - NoDiskConflict (predicates.go:275): shared conflict token where not
      both mounts are read-only (GCE-PD/ISCSI/RBD escape; EBS never does).
    - MaxPDVolumeCount (:404) + CSI limits (csi_volume_predicate.go:54):
      per-kind unique-volume counts vs per-node attach limits.

    All terms are pod-row-local (no cross-pod segments), so single-row pod
    slices in the serial parity path evaluate correctly.
    """
    P, N = pods.req.shape[0], nodes.allocatable.shape[0]
    reasons = jnp.zeros((P, N), jnp.int32)

    # ---- NoDiskConflict --------------------------------------------------
    esc = vol.conflict_escape  # (Uv,)
    conflicts = (
        (pods.vol_any_mh * (1.0 - esc)) @ nodes.vol_any_mh.T
        + (pods.vol_any_mh * esc) @ nodes.vol_rw_mh.T
        + (pods.vol_rw_mh * esc) @ nodes.vol_any_mh.T
    )
    reasons |= jnp.where(conflicts > 0, jnp.int32(1 << BIT["NoDiskConflict"]), 0)

    # ---- MaxPDVolumeCount (4 in-tree kinds, statically unrolled) ---------
    # each checker quick-returns when the pod has no relevant volumes
    # (predicates.go:471), so limits only bind pods that carry that kind —
    # including pods whose volumes are all already mounted on an over-limit
    # node (numNewVolumes may be 0 but the count check still runs :516)
    count_fail = jnp.zeros((P, N), bool)
    for t in range(vol.pd_type_onehot.shape[1]):
        tm = vol.pd_type_onehot[:, t]  # (Uvd,)
        podt = pods.pd_mh * tm
        nodet = nodes.pd_mh * tm
        has_t = jnp.sum(podt, axis=1) > 0  # (P,)
        node_cnt = jnp.sum(nodet, axis=1)  # (N,)
        new = jnp.sum(podt, axis=1)[:, None] - podt @ nodet.T  # (P, N)
        over = node_cnt[None, :] + new > nodes.pd_limit[:, t][None, :]
        count_fail |= has_t[:, None] & over

    # ---- CSI per-driver limits ------------------------------------------
    # the CSI checker only examines drivers the pod *adds* volumes for
    # (csi_volume_predicate.go:104 iterates newVolumeCount), so an
    # already-mounted-only pod passes even on an over-limit node
    for d in range(vol.csi_driver_onehot.shape[1]):
        dm = vol.csi_driver_onehot[:, d]
        podd = pods.csi_mh * dm
        noded = nodes.csi_mh * dm
        node_cnt = jnp.sum(noded, axis=1)
        new = jnp.sum(podd, axis=1)[:, None] - podd @ noded.T
        over = node_cnt[None, :] + new > nodes.csi_limit[:, d][None, :]
        count_fail |= (new > 0) & over
    reasons |= jnp.where(count_fail, jnp.int32(1 << BIT["MaxVolumeCount"]), 0)
    return reasons


def static_volume_reasons(
    pods: DevicePods, nodes: DeviceNodes, sel: DeviceSelectors, vol,
    prog: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Usage-independent volume predicates, computed once per scheduling
    cycle and ORed into every round's reasons via ``static_reasons``:

    - NoVolumeZoneConflict (predicates.go:632): bound PVs' failure-domain
      labels vs node labels.
    - CheckVolumeBinding (:1666): PV node-affinity CNF over selector
      programs (rows reference this pod batch, so this must be evaluated
      against the same batch layout as ``pack_pods``).
    - VolumeError: unresolvable PVC/PV state fails the pod everywhere.

    ``prog`` accepts the selector table from
    :func:`static_predicate_reasons` so a cycle evaluates the (G, N)
    program match once, not twice.
    """
    P, N = pods.req.shape[0], nodes.allocatable.shape[0]
    reasons = jnp.zeros((P, N), jnp.int32)
    if prog is None:
        prog = selector_program_match(sel, nodes)  # (G, N)

    # ---- NoVolumeZoneConflict -------------------------------------------
    # row passes where the node carries an allowed (key, value) pair or has
    # no zone labels at all (the nodeConstraints fast path)
    row_hit = (vol.vz_pairs_mh @ nodes.pair_mh.T) > 0  # (Rv, N)
    row_bad = (~row_hit) & nodes.has_zone_label[None, :] & vol.vz_valid[:, None]
    vz_bad = jax.ops.segment_max(
        row_bad.astype(jnp.int32), vol.vz_pod, num_segments=P
    )  # (P, N)
    reasons |= jnp.where(vz_bad > 0, jnp.int32(1 << BIT["NoVolumeZoneConflict"]), 0)

    # ---- CheckVolumeBinding (CNF over PV-affinity programs) -------------
    Cb = vol.vb_clause_pod.shape[0]
    row_m = prog[jnp.clip(vol.vb_row_prog, 0, prog.shape[0] - 1)]  # (Rb, N)
    row_m = row_m & vol.vb_row_valid[:, None]
    clause_ok = (
        jax.ops.segment_max(
            row_m.astype(jnp.int32), vol.vb_row_clause, num_segments=Cb
        )
        > 0
    )  # (Cb, N); a clause with no rows (no candidate PV) stays False
    clause_bad = (~clause_ok) & vol.vb_clause_valid[:, None]
    bound_bad = jax.ops.segment_max(
        (clause_bad & vol.vb_clause_bound[:, None]).astype(jnp.int32),
        vol.vb_clause_pod,
        num_segments=P,
    )
    unbound_bad = jax.ops.segment_max(
        (clause_bad & ~vol.vb_clause_bound[:, None]).astype(jnp.int32),
        vol.vb_clause_pod,
        num_segments=P,
    )
    reasons |= jnp.where(bound_bad > 0, jnp.int32(1 << BIT["VolumeNodeConflict"]), 0)
    reasons |= jnp.where(unbound_bad > 0, jnp.int32(1 << BIT["VolumeBindConflict"]), 0)

    # ---- unresolvable volume state: fails everywhere --------------------
    reasons |= jnp.where(
        pods.vol_error[:, None], jnp.int32(1 << BIT["VolumeError"]), 0
    )
    return reasons


def resource_fit_mask(
    pod_req: jnp.ndarray, allocatable: jnp.ndarray, requested: jnp.ndarray
) -> jnp.ndarray:
    """(P, N) bool resource-only fit — reused by the assignment inner loop
    where usage changes as pods land (the dynamic analog of the reference
    re-running PodFitsResources per scheduling cycle).

    Iterates the (small, static) resource axis so no (P, N, R) intermediate
    is materialized — each column is one (P, N) comparison the VPU streams.
    """
    free = allocatable - requested  # (N, R)
    full = None
    nonzero = None
    for r in range(pod_req.shape[1]):
        col = pod_req[:, r : r + 1] <= free[None, :, r] + 1e-6
        full = col if full is None else (full & col)
        if r != RES_PODS:
            nz = pod_req[:, r] > 0
            nonzero = nz if nonzero is None else (nonzero | nz)
    pods_only = pod_req[:, RES_PODS : RES_PODS + 1] <= free[None, :, RES_PODS] + 1e-6
    return jnp.where(nonzero[:, None], full, pods_only)


def pods_have_no_ports(pod_table) -> bool:
    """Host-side gate companion to ``run_predicates(no_ports=)``: True when
    no pending pod in the packed table declares host ports."""
    return (pod_table.port_wild_pp.sum() == 0
            and pod_table.port_spec_pp.sum() == 0
            and pod_table.port_spec_pip.sum() == 0)


def decode_reasons(bitmask: int) -> Tuple[str, ...]:
    """Host helper: failure-reason names from a reasons bitmask entry."""
    return tuple(n for i, n in enumerate(PREDICATE_BITS) if bitmask >> i & 1)
