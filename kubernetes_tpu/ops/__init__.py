from kubernetes_tpu.ops import arrays, predicates  # noqa: F401
