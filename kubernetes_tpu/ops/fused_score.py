"""Pallas TPU kernels for the fused scoring normalize — the single-pass
masked NormalizeReduce pair (VERDICT r4 item 3).

The two hoisted-raw priorities (NodeAffinity forward, TaintToleration
reverse — priorities/reduce.go NormalizeReduce semantics over the
filtered node list, generic_scheduler.go:684) each cost a full (P, N)
masked row-max plus a full (P, N) scale per round. XLA:CPU fuses the
elementwise chains but still materializes per-kernel temporaries and
separate accumulate passes (benchres/solver_profile_cpu.json: the
normalize-reduce family was ~2/3 of scoring). These kernels restructure
the pair into two HBM-minimal passes shared across BOTH priorities:

  pass 1 (_pair_max_kernel): one streaming read of raw_fwd, raw_rev and
      the mask produces both per-pod feasible maxima — tile-accumulated
      in VMEM, never materializing the masked (P, N) temporaries;
  pass 2 (_pair_scale_kernel): one streaming read of both raws scales,
      floors, reverses and WEIGHT-COMBINES into a single (P, N) output —
      the weighted pair lands as one accumulate term.

Total HBM traffic ≈ 5 f32 matrices + 1 bool vs ~9 for the unfused
chain. Per-element arithmetic replicates ops/priorities._idiv and
_normalize_reduce exactly; the row max is computed tile-wise, and f32
max is exact under any association, so the result is bit-identical to
the jnp path (pinned by tests/test_priorities.py in interpret mode and
tests_tpu/ compiled).

Same compile-probe discipline as ops/sinkhorn.py: Mosaic verification
happens inside the caller's jit where try/except can't reach, so the
exact block config is probed once (lru_cached) and failure downgrades
to the fused jnp path instead of killing the solve.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

MAX_PRIORITY = 10.0
_EPS = 1e-5

BLOCK_P, BLOCK_N = 256, 512
#: per-slab VMEM budget (see ops/sinkhorn.py VMEM_SLAB_BUDGET: the axon
#: tunnel's AOT helper enforces a 16 MiB scoped-vmem stack; 4 MiB slabs
#: stay inside it even double-buffered with four live inputs)
VMEM_SLAB_BUDGET = 2 * 1024 * 1024


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _block_shapes(P0: int, N0: int, block_p: int = BLOCK_P,
                  block_n: int = BLOCK_N):
    """(bp, bn, padded P, padded N) — one place for block/padding math so
    probe and real call can never diverge (sinkhorn._block_shapes
    pattern). Both dims multiples of 128; blocks shrink until a
    (bp, bn) f32 slab fits the budget."""
    bp = min(block_p, _round_up(P0, 128))
    bn = min(block_n, _round_up(N0, 128))
    while bp > 128 and bp * bn * 4 > VMEM_SLAB_BUDGET:
        bp -= 128
    while bn > 128 and bp * bn * 4 > VMEM_SLAB_BUDGET:
        bn -= 128
    return bp, bn, _round_up(P0, bp), _round_up(N0, bn)


def _idiv(num, den):
    """ops/priorities._idiv verbatim (Go integer division in f32)."""
    return jnp.floor(num / jnp.maximum(den, 1e-30) + _EPS)


def _pair_max_kernel(rf_ref, rr_ref, m_ref, mxf_ref, mxr_ref):
    """Tile-accumulated masked row maxima for both raws at once."""
    from jax.experimental import pallas as pl

    j = pl.program_id(1)
    m = m_ref[...]
    mf = jnp.max(jnp.where(m, rf_ref[...], 0.0), axis=1)
    mr = jnp.max(jnp.where(m, rr_ref[...], 0.0), axis=1)

    @pl.when(j == 0)
    def _init():
        mxf_ref[0, :] = mf
        mxr_ref[0, :] = mr

    @pl.when(j > 0)
    def _acc():
        mxf_ref[0, :] = jnp.maximum(mxf_ref[0, :], mf)
        mxr_ref[0, :] = jnp.maximum(mxr_ref[0, :], mr)


def _make_pair_scale_kernel(w_fwd: float, w_rev: float):
    def _pair_scale_kernel(rf_ref, rr_ref, mxf_ref, mxr_ref, o_ref):
        rf = rf_ref[...]
        rr = rr_ref[...]
        mxf = mxf_ref[0, :][:, None]
        mxr = mxr_ref[0, :][:, None]
        sf = _idiv(MAX_PRIORITY * rf, jnp.where(mxf > 0, mxf, 1.0))
        sf = jnp.where(mxf > 0, sf, 0.0)
        sr = _idiv(MAX_PRIORITY * rr, jnp.where(mxr > 0, mxr, 1.0))
        sr = jnp.where(mxr > 0, sr, 0.0)
        sr = jnp.where(mxr > 0, MAX_PRIORITY - sr, MAX_PRIORITY)
        o_ref[...] = w_fwd * sf + w_rev * sr

    return _pair_scale_kernel


def _pair_pallas(raw_fwd, raw_rev, mask, w_fwd, w_rev,
                 block_p=BLOCK_P, block_n=BLOCK_N, interpret=False):
    from jax.experimental import pallas as pl

    P0, N0 = raw_fwd.shape
    bp, bn, P, N = _block_shapes(P0, N0, block_p, block_n)
    if (P, N) != (P0, N0):
        # padded rows/cols: mask False -> excluded from maxima; their
        # output values are garbage-free (scale of 0 raws) and sliced off
        raw_fwd = jnp.pad(raw_fwd, ((0, P - P0), (0, N - N0)))
        raw_rev = jnp.pad(raw_rev, ((0, P - P0), (0, N - N0)))
        mask = jnp.pad(mask, ((0, P - P0), (0, N - N0)))
    mxf, mxr = pl.pallas_call(
        _pair_max_kernel,
        grid=(P // bp, N // bn),
        in_specs=[
            pl.BlockSpec((bp, bn), lambda i, j: (i, j)),
            pl.BlockSpec((bp, bn), lambda i, j: (i, j)),
            pl.BlockSpec((bp, bn), lambda i, j: (i, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, bp), lambda i, j: (0, i)),
            pl.BlockSpec((1, bp), lambda i, j: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, P), raw_fwd.dtype),
            jax.ShapeDtypeStruct((1, P), raw_fwd.dtype),
        ],
        interpret=interpret,
    )(raw_fwd, raw_rev, mask)
    out = pl.pallas_call(
        _make_pair_scale_kernel(float(w_fwd), float(w_rev)),
        grid=(P // bp, N // bn),
        in_specs=[
            pl.BlockSpec((bp, bn), lambda i, j: (i, j)),
            pl.BlockSpec((bp, bn), lambda i, j: (i, j)),
            pl.BlockSpec((1, bp), lambda i, j: (0, i)),
            pl.BlockSpec((1, bp), lambda i, j: (0, i)),
        ],
        out_specs=pl.BlockSpec((bp, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((P, N), raw_fwd.dtype),
        interpret=interpret,
    )(raw_fwd, raw_rev, mxf, mxr)
    return out[:P0, :N0]


@functools.lru_cache(maxsize=64)
def _pallas_compiles(bp: int, bn: int, P: int, N: int) -> bool:
    """One-time Mosaic compile probe at the exact padded shape + block
    config (sinkhorn._pallas_compiles pattern)."""
    try:
        # graftlint: disable=R3 -- one-time compile probe, memoized by the
        # lru_cache above: the wrapper is built once per (block, shape) key
        out = jax.jit(functools.partial(
            _pair_pallas, w_fwd=1.0, w_rev=1.0, block_p=bp, block_n=bn))(
            jnp.zeros((P, N), jnp.float32),
            jnp.zeros((P, N), jnp.float32),
            jnp.zeros((P, N), bool),
        )
        jax.block_until_ready(out)
        return True
    except Exception:
        return False


def use_pallas() -> bool:
    """On by default on real TPU; KTPU_PALLAS=1 forces interpret mode
    (testing), =0 disables (same policy as ops/sinkhorn.use_pallas)."""
    env = os.environ.get("KTPU_PALLAS", "")
    if env == "0":
        return False
    if env == "1":
        return True
    return jax.default_backend() == "tpu"


def fused_pair_normalize_device(raw_fwd, raw_rev, mask, w_fwd, w_rev):
    """Backend-routing entry: the Pallas two-pass pair on TPU (probe
    permitting), else None — the caller (priorities._fused_pair_normalize)
    keeps its fused jnp expression as the universal fallback."""
    if not use_pallas():
        return None
    interp = jax.default_backend() != "tpu"
    if not interp and not _pallas_compiles(*_block_shapes(*raw_fwd.shape)):
        return None
    return _pair_pallas(raw_fwd, raw_rev, mask, w_fwd, w_rev,
                        interpret=interp)


# ---------------------------------------------------------------------------
# Incremental-solve score/feasibility cache (docs/perf.md "incremental
# solve"): a device-resident per-node summary of the score plane, kept
# coherent with the resident NodeTable by the SAME full-vs-delta
# discipline (SchedulerCache maintains it right where it maintains the
# snapshot — full rebuilds recompute it wholesale, delta cycles patch
# exactly the scattered rows with a donated scatter, clean cycles touch
# nothing). The restricted solve then picks its candidate node columns
# from this cached plane in O(N log C) instead of re-scoring the full
# (P, N) plane: clean columns are REUSED across cycles; only dirty
# columns (bind/delete/update-touched nodes) were recomputed.
# ---------------------------------------------------------------------------

from typing import NamedTuple


class NodeSummary(NamedTuple):
    """The cached per-node slice of the score/feasibility plane.

    ``eligible`` — the pod-independent feasibility column: node valid,
    schedulable, condition-clean (when the Policy enforces the
    condition predicates), and with at least one free pod slot. The
    pod-CONDITIONED predicate residual (selectors, taints, resources
    against the actual request) is re-evaluated by the restricted solve
    itself on the gathered candidate columns — this column only decides
    which columns are worth gathering.

    ``rank`` — the candidate ranking score (generic lean objective over
    free-capacity fractions; sign flipped under a packing objective).
    Ineligible columns carry ``-inf`` so they can never out-rank a live
    one."""

    eligible: jnp.ndarray  # (N,) bool
    rank: jnp.ndarray  # (N,) f32, -inf on ineligible columns


#: rank boost that guarantees dirty columns survive the top-k cut —
#: finite (padding-safe) but far above any free-fraction rank in [0, 1]
DIRTY_BOOST = 1e6

#: rank boost for group-hinted columns (a gang's home-slice columns, a
#: scenario pack's candidate hint): guaranteed a slot ahead of every
#: plain rank but BELOW the dirty boost — the churn frontier always
#: wins the quota contest (docs/perf.md "Sparsity-first solve")
HINT_BOOST = 1e5

_NEG = -3e38  # ineligible-column rank (finite: top_k handles -inf fine,
# but a finite sentinel keeps the padded-index arithmetic NaN-free)


@functools.partial(jax.jit, static_argnames=("honor_conditions",
                                             "prefer_packed"))
def node_summary(nodes, honor_conditions=True, prefer_packed=False):
    """Compute the per-node summary from a DeviceNodes table (full
    rebuild) or from a delta sub-table (whose rows then scatter in via
    :func:`patch_node_summary`). One streaming pass over the (N, R)
    usage columns and the (N,) condition bits; no (P, N) work.

    ``honor_conditions`` mirrors whether the Policy enforces the node
    condition predicates — when it does not, pressured/not-ready nodes
    stay candidate-eligible exactly as the cold solve would admit them.
    ``prefer_packed`` flips the ranking for packing-style objectives
    (MostRequestedPriority outweighing LeastRequested): fullest-first
    instead of freest-first."""
    from kubernetes_tpu.snapshot import RES_CPU, RES_MEM, RES_PODS

    free = nodes.allocatable - nodes.requested  # (N, R)
    eligible = nodes.valid
    if honor_conditions:
        eligible = (eligible & nodes.schedulable & nodes.ready
                    & ~nodes.network_unavailable & ~nodes.mem_pressure
                    & ~nodes.disk_pressure & ~nodes.pid_pressure)
    # a column with no free pod slot cannot admit anything this cycle —
    # not worth a candidate slot even under a packing objective
    eligible = eligible & (free[:, RES_PODS] >= 1.0)

    def frac(col):
        cap = nodes.allocatable[:, col]
        return jnp.where(cap > 0, jnp.maximum(free[:, col], 0.0)
                         / jnp.maximum(cap, 1e-30), 0.0)

    rank = 0.5 * (frac(RES_CPU) + frac(RES_MEM))
    if prefer_packed:
        rank = 1.0 - rank
    return NodeSummary(eligible=eligible,
                       rank=jnp.where(eligible, rank, _NEG))


@functools.partial(jax.jit, donate_argnums=(0,))
def _patch_node_summary_donated(summary, sub, idx):
    """Scatter delta rows into the resident summary — the same donated
    single-scatter discipline as ops/arrays._scatter_node_rows_donated
    (XLA aliases the output onto the existing buffers, preserving the
    resident sharding on a mesh; padded idx slots point out of bounds
    and drop)."""
    return NodeSummary(
        eligible=summary.eligible.at[idx].set(sub.eligible, mode="drop"),
        rank=summary.rank.at[idx].set(sub.rank, mode="drop"),
    )


def patch_node_summary(summary, sub, idx):
    """Jitted row-patch entry: ``idx`` (D,) host indices aligned with
    ``sub``'s rows; entries >= the resident row count drop (padding).
    The resident ``summary``'s buffers are donated — do not reuse."""
    return _patch_node_summary_donated(summary, sub,
                                       jnp.asarray(idx, jnp.int32))


def _candidate_score(summary, dirty_mask, hint_mask):
    """The shared candidate ranking: plain rank + the dirty-frontier
    boost + (optionally) the group-quota hint boost. Hinted ineligible
    columns stay at the sentinel — a quota can widen the cut, never
    resurrect a dead column."""
    score = summary.rank + jnp.where(dirty_mask & summary.eligible,
                                     DIRTY_BOOST, 0.0)
    if hint_mask is not None:
        score = score + jnp.where(hint_mask & summary.eligible,
                                  HINT_BOOST, 0.0)
    return score


def _merge_local_topk(vals, idx, k):
    """Replicated merge of the per-shard winners: lexicographic sort by
    (value desc, global index asc) over the (shards * k,) pool, take
    the first k. The index tie-break matches ``jax.lax.top_k``'s
    (lower index first), which is what makes the sharded pick
    bit-identical to the single-pass one."""
    neg, sidx = jax.lax.sort((jnp.negative(vals.reshape(-1)),
                              idx.reshape(-1)), num_keys=2)
    return jnp.negative(neg[:k]), sidx[:k]


def _sharded_topk(score, k, num_shards):
    """Top-``k`` of a (N,) score plane, mesh-shardable: ``num_shards >
    1`` selects the two-stage pick — the plane reshapes to (S, N/S), a
    zero-collective VIEW of the node-sharded resident layout (each row
    one shard's contiguous block), each shard top-k's LOCALLY, and only
    the (S, k) winner frame merges replicated
    (:func:`_merge_local_topk`). The global top-k set can take at most
    k entries from any one shard, and both stages break ties on the
    lower global index, so the result is BIT-IDENTICAL to the
    single-pass pick on any shard count — the mesh-parity contract the
    fuzz suite pins. A dense (S, N) or (P, N) plane never
    materializes. Shapes that cannot shard evenly (or k too large for
    a lossless local pick) take the single-pass path."""
    n = score.shape[0]
    if num_shards > 1 and n % num_shards == 0 and k <= n // num_shards:
        local = n // num_shards
        lvals, lidx = jax.lax.top_k(score.reshape(num_shards, local), k)
        offs = (jnp.arange(num_shards, dtype=jnp.int32) * local)[:, None]
        return _merge_local_topk(lvals, lidx.astype(jnp.int32) + offs, k)
    return jax.lax.top_k(score, k)


@functools.partial(jax.jit,
                   static_argnames=("k", "num_shards", "hint_quota"))
def candidate_columns(summary, dirty_mask, k, hint_mask=None,
                      num_shards=1, hint_quota=0):
    """Top-``k`` candidate node columns for the restricted solve: the
    best-ranked eligible columns, with every DIRTY eligible column
    (bind/delete/update-touched this cycle — the churn frontier)
    guaranteed a slot via a rank boost, and every HINTED eligible
    column (a gang's home-slice quota, a scenario pack's candidate
    hint) a slot right behind it. O(N log k), the only full-N work an
    incremental cycle performs. Returns (k,) int32 column indices;
    slots that fell on ineligible columns point one past the table
    (== N) so downstream gathers treat them as padding.

    ``hint_quota > 0`` switches the hint from a boost to a RESERVED
    SPLIT: the first ``hint_quota`` slots hold the top hinted columns
    (dirty boost still applies within the segment), the remaining
    ``k - hint_quota`` hold the top UNHINTED columns — disjoint by
    construction, so a large hint set (a whole home slice) can never
    crowd plain-ranked candidates out of the frame. Quota slots a
    too-small hint set cannot fill come out as padding sentinels
    (harmless: gathered rows reject every predicate).

    The pick shards on the mesh via :func:`_sharded_topk` — per-shard
    local top-k, replicated merge of the (S, k) winners, bit-identical
    to single-pass on any shard count."""
    n = summary.rank.shape[0]
    if hint_mask is not None and 0 < hint_quota < k:
        base = _candidate_score(summary, dirty_mask, None)
        hv, hi = _sharded_topk(jnp.where(hint_mask, base, _NEG),
                               hint_quota, num_shards)
        uv, ui = _sharded_topk(jnp.where(hint_mask, _NEG, base),
                               k - hint_quota, num_shards)
        vals = jnp.concatenate([hv, uv])
        idx = jnp.concatenate([hi, ui])
    else:
        score = _candidate_score(summary, dirty_mask, hint_mask)
        vals, idx = _sharded_topk(score, k, num_shards)
    return jnp.where(vals > _NEG / 2, idx.astype(jnp.int32),
                     jnp.int32(n))


@functools.partial(jax.jit,
                   static_argnames=("n_blocks", "block_width",
                                    "num_shards"))
def partition_columns(summary, dirty_mask, n_blocks, block_width,
                      num_shards=1):
    """Capacity-balanced column blocks for the PARTITIONED COLD solve
    (docs/perf.md "Sparsity-first solve"): take the top
    ``n_blocks * block_width`` columns by rank (one sharded top-k —
    still nothing (P, N)-shaped) and deal them round-robin into
    ``n_blocks`` blocks of ``block_width`` columns each. Two things
    follow from the shape choice:

    - ``block_width`` is the restricted path's candidate bucket C, so
      every block solves through the ALREADY-COMPILED (P, C)
      restricted program — a partitioned cold cycle adds zero new
      solver shapes (the zero-retrace contract);
    - the round-robin deal balances capacity: block b holds ranks
      b, b+B, b+2B, ... so every block spans the rank spectrum and
      block 0 owns the single best column — the first block solve
      places most of a cold batch on an uncontended frame.

    Cold cost stops scaling linearly with N: O(N log(B·C)) selection
    plus B fixed-size (P, C) solves, vs the dense solve's O(P·N)
    plane. Ineligible columns map to the padding sentinel (== N)
    exactly like :func:`candidate_columns` slots. Returns
    (n_blocks, block_width) int32."""
    n = summary.rank.shape[0]
    score = _candidate_score(summary, dirty_mask, None)
    vals, order = _sharded_topk(score, n_blocks * block_width, num_shards)
    idx = jnp.where(vals > _NEG / 2, order.astype(jnp.int32),
                    jnp.int32(n))
    return idx.reshape(block_width, n_blocks).T
