"""Batched assignment — replacing the reference's one-pod-at-a-time driver
loop (``pkg/scheduler/scheduler.go:462`` scheduleOne → ``selectHost``
``generic_scheduler.go:292``) with whole-queue placement on device.

Two solvers:

- ``greedy_assign`` — the **parity path**: a ``lax.scan`` over pods in
  activeQ order (priority desc, arrival asc — the queue comparator,
  ``internal/queue/scheduling_queue.go``), recomputing predicates+priorities
  for the one pod against the *current* usage state each step. Bit-for-bit
  the reference's serial semantics (modulo selectHost's randomized
  round-robin tie-break: we take the lowest node index deterministically).

- ``batch_assign`` — the **fast path**: assign-and-mask rounds. Every round,
  all unplaced pods score all nodes at once (MXU), argmax their best node,
  and per-node acceptance admits the highest-priority prefix that fits
  capacity (segmented prefix sums); usage updates by scatter-add and the
  next round re-masks. Contended capacity thus resolves in O(rounds)
  full-matrix passes instead of O(pods) serial cycles.

Pods with host ports get conservative treatment in the fast path (one
port-bearing pod per node per round) so intra-batch port conflicts can
never be admitted; the round structure retries the rest.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops.arrays import DeviceNodes, DevicePods, DeviceSelectors
from kubernetes_tpu.ops.predicates import (
    run_predicates,
    static_predicate_reasons,
    static_volume_reasons,
)
from kubernetes_tpu.ops.priorities import run_priorities

NEG = -1e30

#: auto-routing thresholds (batch_assign auto_sinkhorn): route a batch
#: to the transport plan only when round 0 shows a REAL tie-contention
#: cohort — at least this many bidders whose multi-way-tied best
#: columns are oversubscribed...
AUTO_TIE_MIN_COHORT = 8
#: ...AND whose runner-up gaps differ by at least this many score steps
#: (heterogeneous opportunity cost is what per-pod argmax cannot see;
#: a homogeneous cohort reaches the OT outcome through rotation +
#: score-ordered admission — the r3 margin-ordered evidence).
AUTO_TIE_GAP_MARGIN = 2.0

#: kernels that can create the asymmetric-second-choice signature the
#: auto-router hunts. When the host-side gates prove ALL of them absent
#: from a batch (solver_gates skip list), every pod's score row is
#: resource-shaped and tie cohorts are gap-homogeneous by construction —
#: the router is compiled OUT for that batch (zero overhead on the
#: gated-light fast path; measured +50% otherwise at 1000x4096).
_PREFERENCE_KERNELS = (
    "NodeAffinityPriority", "SelectorSpreadPriority",
    "InterPodAffinityPriority", "EvenPodsSpreadPriority",
    "TaintTolerationPriority", "ImageLocalityPriority",
)


class UsageState(NamedTuple):
    """The mutable slice of node state — what AddPod touches in the
    reference's NodeInfo (node_info.go AddPod: requested, nonZeroRequest,
    usedPorts, pod list) plus spread counts."""

    requested: jnp.ndarray  # (N, R)
    nonzero_req: jnp.ndarray  # (N, 2)
    port_any: jnp.ndarray  # (N, Upp)
    port_wild: jnp.ndarray  # (N, Upp)
    port_spec: jnp.ndarray  # (N, Upip)
    owner_counts: jnp.ndarray  # (N, Uo)
    matcher_counts: jnp.ndarray  # (N, M)
    anti_counts: jnp.ndarray  # (N, Ua)
    sym_counts: jnp.ndarray  # (N, Us)
    aff_pod_count: jnp.ndarray  # (N,)
    vol_any: jnp.ndarray  # (N, Uv)
    vol_rw: jnp.ndarray  # (N, Uv)
    pd_mh: jnp.ndarray  # (N, Uvd)
    csi_mh: jnp.ndarray  # (N, Uvc)


def usage_from_nodes(nodes: DeviceNodes) -> UsageState:
    return UsageState(
        requested=nodes.requested,
        nonzero_req=nodes.nonzero_req,
        port_any=nodes.port_any_mh,
        port_wild=nodes.port_wild_mh,
        port_spec=nodes.port_spec_mh,
        owner_counts=nodes.owner_counts,
        matcher_counts=nodes.matcher_counts,
        anti_counts=nodes.anti_counts,
        sym_counts=nodes.sym_counts,
        aff_pod_count=nodes.aff_pod_count,
        vol_any=nodes.vol_any_mh,
        vol_rw=nodes.vol_rw_mh,
        pd_mh=nodes.pd_mh,
        csi_mh=nodes.csi_mh,
    )


def nodes_with_usage(nodes: DeviceNodes, u: UsageState) -> DeviceNodes:
    return nodes._replace(
        requested=u.requested,
        nonzero_req=u.nonzero_req,
        port_any_mh=u.port_any,
        port_wild_mh=u.port_wild,
        port_spec_mh=u.port_spec,
        owner_counts=u.owner_counts,
        matcher_counts=u.matcher_counts,
        anti_counts=u.anti_counts,
        sym_counts=u.sym_counts,
        aff_pod_count=u.aff_pod_count,
        vol_any_mh=u.vol_any,
        vol_rw_mh=u.vol_rw,
        pd_mh=u.pd_mh,
        csi_mh=u.csi_mh,
    )


def _apply_batch(u: UsageState, pods: DevicePods, node_idx: jnp.ndarray,
                 accepted: jnp.ndarray) -> UsageState:
    """Scatter accepted pods into the usage state. ``node_idx`` (P,) row per
    pod; ``accepted`` (P,) bool gates contributions (rejected rows scatter
    zeros into row 0 harmlessly)."""
    tgt = jnp.where(accepted, node_idx, 0)
    w = accepted.astype(jnp.float32)[:, None]
    return UsageState(
        requested=u.requested.at[tgt].add(pods.req * w),
        nonzero_req=u.nonzero_req.at[tgt].add(pods.nonzero_req * w),
        port_any=u.port_any.at[tgt].max(
            jnp.maximum(pods.port_wild_pp, pods.port_spec_pp) * w
        ),
        port_wild=u.port_wild.at[tgt].max(pods.port_wild_pp * w),
        port_spec=u.port_spec.at[tgt].max(pods.port_spec_pip * w),
        owner_counts=u.owner_counts.at[tgt].add(pods.owner_match_mh * w),
        matcher_counts=u.matcher_counts.at[tgt].add(pods.matcher_mh * w),
        anti_counts=u.anti_counts.at[tgt].add(pods.anti_term_mh * w),
        sym_counts=u.sym_counts.at[tgt].add(pods.sym_term_mh * w),
        aff_pod_count=u.aff_pod_count.at[tgt].add(
            pods.has_aff.astype(jnp.float32) * w[:, 0]
        ),
        vol_any=u.vol_any.at[tgt].max(pods.vol_any_mh * w),
        vol_rw=u.vol_rw.at[tgt].max(pods.vol_rw_mh * w),
        pd_mh=u.pd_mh.at[tgt].max(pods.pd_mh * w),
        csi_mh=u.csi_mh.at[tgt].max(pods.csi_mh * w),
    )


def _pod_slice(pods: DevicePods, p: jnp.ndarray) -> DevicePods:
    """One-row DevicePods view at dynamic index p (static shapes)."""
    take = lambda a: jax.lax.dynamic_index_in_dim(a, p, axis=0, keepdims=True)
    return DevicePods(*[take(f) for f in pods])


def queue_order(pods: DevicePods) -> jnp.ndarray:
    """activeQ comparator: priority desc, then arrival (row order) asc —
    scheduling_queue.go's podsCompareBackoffCompleted/less func analog.
    Invalid (padding) rows sort last."""
    pri = jnp.where(pods.valid, pods.priority, jnp.iinfo(jnp.int32).min)
    return jnp.lexsort((pods.order, -pri))


@partial(jax.jit, static_argnames=("weights_key", "skip_key", "no_ports",
                                   "no_pod_affinity", "no_spread"))
def _greedy_impl(pods, nodes, sel, topo, vol, weights_key, extra_mask,
                 static_vol=None, enabled_mask=None, extra_score=None,
                 skip_key=(), no_ports=False, no_pod_affinity=False,
                 no_spread=False):
    weights = dict(weights_key) if weights_key is not None else None
    P = pods.req.shape[0]
    perm = queue_order(pods)
    u0 = usage_from_nodes(nodes)
    # static predicate bits hoisted out of the scan; each step slices its row
    static_bits, prog = static_predicate_reasons(pods, nodes, sel)
    if vol is not None and static_vol is None:
        static_vol = static_volume_reasons(pods, nodes, sel, vol, prog=prog)

    def step(u, p):
        pod = _pod_slice(pods, p)
        cur = nodes_with_usage(nodes, u)
        extra = jax.lax.dynamic_index_in_dim(extra_mask, p, axis=0, keepdims=True)
        sv = (
            jax.lax.dynamic_index_in_dim(static_vol, p, axis=0, keepdims=True)
            if static_vol is not None
            else None
        )
        sb = jax.lax.dynamic_index_in_dim(static_bits, p, axis=0, keepdims=True)
        mask = (
            run_predicates(pod, cur, sel, topo, vol, sv, enabled_mask,
                           hoisted=(sb, prog), no_ports=no_ports,
                           no_pod_affinity=no_pod_affinity,
                           no_spread=no_spread).mask
            & extra
        )  # (1, N)
        score = run_priorities(pod, cur, sel, mask, weights, topo,
                               skip=skip_key)
        if extra_score is not None:
            score = score + jax.lax.dynamic_index_in_dim(
                extra_score, p, axis=0, keepdims=True
            )
        masked = jnp.where(mask, score, NEG)
        best = jnp.argmax(masked[0])
        ok = mask[0, best] & pod.valid[0]
        u = _apply_batch(u, pod, best[None], ok[None])
        return u, jnp.where(ok, best.astype(jnp.int32), -1)

    u, picks = jax.lax.scan(step, u0, perm)
    assigned = jnp.full((P,), -1, jnp.int32).at[perm].set(picks)
    return assigned, u


def greedy_assign(
    pods: DevicePods,
    nodes: DeviceNodes,
    sel: DeviceSelectors,
    weights: Optional[Dict[str, float]] = None,
    topo=None,
    extra_mask: Optional[jnp.ndarray] = None,
    vol=None,
    static_vol: Optional[jnp.ndarray] = None,
    enabled_mask: Optional[int] = None,
    extra_score: Optional[jnp.ndarray] = None,
    skip_priorities=(),
    no_ports: bool = False,
    no_pod_affinity: bool = False,
    no_spread: bool = False,
    fault_hook=None,
    fault_site: str = "solve:greedy",
) -> Tuple[jnp.ndarray, UsageState]:
    """Serial-parity solver. Returns (assigned node row per pod or -1,
    final usage). ``extra_mask`` (P, N) ANDs into feasibility — the driver
    feeds the nominated-pods pass-A mask through it (podFitsOnNode's
    two-pass rule, generic_scheduler.go:610). ``skip_priorities``: names
    from :func:`~kubernetes_tpu.ops.priorities.empty_priorities`, whose
    kernels are replaced by their exact constants (static jit key).

    ``fault_hook(site, assigned, usage, rounds, n_nodes)`` is the
    solver-entry fault-injection seam (kubernetes_tpu/faults.py): called
    with the would-be result, it may raise a SolverFault or return a
    poisoned triple — exactly what an out-of-process solver timing out
    or lying over the wire would look like to the driver."""
    key = tuple(sorted(weights.items())) if weights is not None else None
    if extra_mask is None:
        extra_mask = jnp.ones(
            (pods.req.shape[0], nodes.allocatable.shape[0]), bool
        )
    assigned, u = _greedy_impl(pods, nodes, sel, topo, vol, key, extra_mask,
                               static_vol, enabled_mask, extra_score,
                               skip_key=tuple(skip_priorities),
                               no_ports=no_ports,
                               no_pod_affinity=no_pod_affinity,
                               no_spread=no_spread)
    if fault_hook is not None:
        assigned, u, _ = fault_hook(fault_site, assigned, u, 0,
                                    nodes.allocatable.shape[0])
    return assigned, u


def _segment_prefix(values: jnp.ndarray, seg_starts: jnp.ndarray) -> jnp.ndarray:
    """Exclusive prefix sums within contiguous segments. ``values`` (P, R)
    sorted by segment; ``seg_starts`` (P,) index of each row's segment
    start."""
    excl = jnp.cumsum(values, axis=0) - values
    return excl - excl[seg_starts]


def _inverse_permutation(perm: jnp.ndarray) -> jnp.ndarray:
    """inv[perm[i]] = i for a permutation ``perm``. ``argsort`` of a
    permutation IS its inverse, and XLA lowers it to one vectorized sort —
    the scatter spelling (``zeros.at[perm].set(iota)``) lowers on XLA:CPU
    to a P-trip while loop of one-element dynamic-update-slices (profiled:
    the two rank scatters were a measurable slice of every round)."""
    return jnp.argsort(perm).astype(jnp.int32)


#: lean-score support: the usage-dependent resource kernels the fused
#: round can inline (shared-fraction form), plus EqualPriority (a
#: constant). Everything else (or a non-integer weight, or a rebound
#: registry name) routes the batch to the general round path.
_LEAN_DYNAMIC = ("LeastRequestedPriority", "MostRequestedPriority",
                 "BalancedResourceAllocation")


def _lean_score_plan(weights_key, skip_key):
    """Host-side (trace-time) scoring plan for the fused round: returns
    ``(const_total, terms)`` — the exact scalar sum of all gated/constant
    kernels plus the ordered (name, weight) list of live resource kernels
    — or None when any active kernel falls outside the provably-exact
    lean set. Exactness mirrors priorities._fusable: every stock kernel
    floors to integer-valued f32 and every weight is an integer, so all
    partial sums are exact f32 integers and regrouping cannot round."""
    from kubernetes_tpu.ops.priorities import (
        _ALL_STOCK_KERNELS,
        _STOCK_KERNELS,
        DEFAULT_WEIGHTS,
        EMPTY_CONSTANTS,
        PRIORITY_REGISTRY,
    )

    weights = (dict(weights_key) if weights_key is not None
               else DEFAULT_WEIGHTS)
    const_total = 0.0
    terms = []
    for name, w in weights.items():
        if not w:
            continue
        if float(w) != int(w):
            return None
        if PRIORITY_REGISTRY.get(name) is not _ALL_STOCK_KERNELS.get(name):
            return None  # rebound kernel: empty/lean behavior unknown
        if (name in skip_key and name in EMPTY_CONSTANTS
                and PRIORITY_REGISTRY[name] is _STOCK_KERNELS[name]):
            const_total += w * EMPTY_CONSTANTS[name]
        elif name == "EqualPriority":
            const_total += w * 1.0
        elif name in _LEAN_DYNAMIC:
            terms.append((name, float(w)))
        else:
            return None
    return const_total, tuple(terms)


def _lean_masked_score(pods, nodes, u, active, static_ok, res_on, plan):
    """The fused round's single (P, N) pass: feasibility mask and weighted
    score in one expression, emitted as ``ms = where(mask, score, NEG)``
    so XLA materializes exactly ONE (P, N) f32 matrix per round instead
    of mask + per-kernel score temporaries. Arithmetic is verbatim
    priorities.least_requested / most_requested / balanced_allocation
    with the shared request fractions computed once (regrouped
    accumulation exact per :func:`_lean_score_plan`)."""
    from kubernetes_tpu.ops.predicates import resource_fit_mask
    from kubernetes_tpu.ops.priorities import MAX_PRIORITY, _EPS, _idiv

    const_total, terms = plan
    mask = static_ok & active[:, None]
    if res_on:
        mask = mask & resource_fit_mask(pods.req, nodes.allocatable,
                                        u.requested)
    score = jnp.full((pods.req.shape[0], nodes.allocatable.shape[0]),
                     jnp.float32(const_total))
    if terms:
        # shared ResourceAllocationPriority scaffold (computed once)
        cpu_req = pods.nonzero_req[:, 0:1] + u.nonzero_req[None, :, 0]
        mem_req = pods.nonzero_req[:, 1:2] + u.nonzero_req[None, :, 1]
        cpu_cap = nodes.allocatable[None, :, 0]
        mem_cap = nodes.allocatable[None, :, 1]

        def capped(req, cap, s):
            return jnp.where((cap <= 0) | (req > cap), 0.0, s)

        for name, w in terms:
            if name == "LeastRequestedPriority":
                t = _idiv(
                    capped(cpu_req, cpu_cap,
                           _idiv((cpu_cap - cpu_req) * MAX_PRIORITY, cpu_cap))
                    + capped(mem_req, mem_cap,
                             _idiv((mem_cap - mem_req) * MAX_PRIORITY,
                                   mem_cap)),
                    2.0)
            elif name == "MostRequestedPriority":
                t = _idiv(
                    capped(cpu_req, cpu_cap,
                           _idiv(cpu_req * MAX_PRIORITY, cpu_cap))
                    + capped(mem_req, mem_cap,
                             _idiv(mem_req * MAX_PRIORITY, mem_cap)),
                    2.0)
            else:  # BalancedResourceAllocation
                cf = jnp.where(cpu_cap > 0,
                               cpu_req / jnp.maximum(cpu_cap, 1e-30), 1.0)
                mf = jnp.where(mem_cap > 0,
                               mem_req / jnp.maximum(mem_cap, 1e-30), 1.0)
                t = jnp.floor((1.0 - jnp.abs(cf - mf)) * MAX_PRIORITY + _EPS)
                t = jnp.where((cf >= 1.0) | (mf >= 1.0), 0.0, t)
            score = score + w * t
    return jnp.where(mask, score, NEG)


def _blocked_pick(tied, arank):
    """Exact (rot+1)-th-set-bit selection without the (P, N) cumsum or the
    (P, N) argmax (profiled at 23 ms + 16 ms per round on XLA:CPU at the
    headline shape — scan-shaped lowerings that don't vectorize): count
    set bits per 64-column block (a fast reduce), locate the target block
    with a (P, N/64) scan, then rank within the one gathered block. The
    chosen column is bit-identical to the cumsum spelling; rotation
    semantics (``rot = arank % tcount``) are unchanged."""
    P, N = tied.shape
    BL = min(64, N)
    if N % BL:
        # non-bucketed node axis (every in-repo caller pads to a
        # power-of-two bucket, but pad_to is an open parameter): the
        # blocked reshape can't apply — take the full-width cumsum
        # spelling, same picks
        pos = jnp.cumsum(tied.astype(jnp.int32), axis=1)
        tcount = pos[:, -1]
        rot = jnp.where(tcount > 0, arank % jnp.maximum(tcount, 1), 0)
        pick = tied & (pos == (rot + 1)[:, None])
        choice = jnp.argmax(pick, axis=1).astype(jnp.int32)
        return jnp.where(tcount > 0, choice, 0), tcount
    t3 = tied.reshape(P, N // BL, BL)
    return _blocked_pick_core(
        t3, lambda bidx: jnp.take_along_axis(
            t3, bidx[:, None, None], axis=1)[:, 0, :],
        arank)


def _blocked_pick_core(t3, gather_block, arank):
    """Shared core of the blocked selection. ``t3`` is the (P, N/BL, BL)
    tied view; ``gather_block(bidx) -> (P, BL) bool`` re-derives one
    block's tied bits (the lean path recomputes them from the gathered
    masked-score slice so the full tied matrix is never materialized).
    Block counts ride int8 (BL <= 64 < 127) — XLA:CPU materializes the
    reduce's convert, and a quarter-width buffer is a quarter of that
    traffic."""
    P = t3.shape[0]
    BL = t3.shape[2]
    bc = jnp.sum(t3.astype(jnp.int8), axis=2,
                 dtype=jnp.int8).astype(jnp.int32)  # (P, N/BL)
    bexcl = jnp.cumsum(bc, axis=1) - bc  # exclusive block prefix
    tcount = bexcl[:, -1] + bc[:, -1]
    rot = jnp.where(tcount > 0, arank % jnp.maximum(tcount, 1), 0)
    hit = (bexcl <= rot[:, None]) & (bexcl + bc > rot[:, None])
    bidx = jnp.argmax(hit, axis=1).astype(jnp.int32)
    blk = gather_block(bidx)
    want = rot - jnp.take_along_axis(bexcl, bidx[:, None], axis=1)[:, 0]
    cpos = jnp.cumsum(blk.astype(jnp.int32), axis=1)  # (P, BL) — small
    inblk = blk & (cpos == (want + 1)[:, None])
    off = jnp.argmax(inblk, axis=1).astype(jnp.int32)
    choice = jnp.where(tcount > 0, bidx * BL + off, 0)
    return choice, tcount


def _admit_scored(choice, rank, req, free, per_node_cap, capacity_on,
                  sorted_gate=None):
    """Score-ordered per-node admission — ONE spelling shared by the
    general and lean round bodies (their bit-identity is the module's
    core claim, so the rule lives in one place): group chosen pods by
    node (queue rank ascending within a node), admit the prefix that
    fits remaining capacity (``capacity_on`` is the trace-time
    PodFitsResources gate), cap admissions per node per round.
    ``sorted_gate(order2, seg_starts) -> (P,) bool`` lets the general
    path AND in its port-conflict guard in the same sorted frame.
    Returns the (P,) accepted mask in original row order."""
    P = choice.shape[0]
    big = jnp.int32(free.shape[0] + 1)
    ckey = jnp.where(choice >= 0, choice, big)
    order2 = jnp.lexsort((rank, ckey))  # grouped by chosen node, rank asc
    c_s = choice[order2]
    ckey_s = ckey[order2]  # sorted — safe for searchsorted
    req_s = req[order2]
    seg_starts = jnp.searchsorted(ckey_s, ckey_s, side="left")
    prefix = _segment_prefix(req_s, seg_starts)  # usage by earlier pods
    free_s = free[jnp.clip(c_s, 0, free.shape[0] - 1)]
    if capacity_on:
        fits = jnp.all(prefix + req_s <= free_s + 1e-6, axis=1)
    else:
        # a Policy bypassing PodFitsResources must also bypass the
        # in-round capacity admission guard (it exists only to keep
        # same-round co-admissions consistent with that predicate)
        fits = jnp.ones((P,), bool)
    within = jnp.arange(P, dtype=jnp.int32) - seg_starts
    acc_s = (c_s >= 0) & fits & (within < per_node_cap)
    if sorted_gate is not None:
        acc_s = acc_s & sorted_gate(order2, seg_starts)
    return acc_s[_inverse_permutation(order2)]


def _lean_rounds(pods, nodes, sel, rank, lean_plan, max_rounds,
                 per_node_cap, enabled_mask):
    """The fused round loop for lean batches (see the routing comment in
    :func:`_batch_impl`). Same carry, same three-exit cond, same
    admission rule; one materialized (P, N) matrix per round."""
    from kubernetes_tpu.ops.predicates import BIT

    P = pods.req.shape[0]
    N = nodes.allocatable.shape[0]
    static_reasons, _prog = static_predicate_reasons(pods, nodes, sel)
    if enabled_mask is not None:
        static_reasons = static_reasons & jnp.int32(enabled_mask)
    static_ok = (static_reasons == 0) & nodes.valid[None, :] \
        & pods.valid[:, None]
    res_on = enabled_mask is None or bool(
        enabled_mask & (1 << BIT["PodFitsResources"]))
    window = N * per_node_cap
    guard = NEG * 0.5  # real scores are finite and tiny next to NEG

    def round_body(carry):
        assigned, u, _, rnd, use_plan, sk_stats = carry
        active = (assigned == -1) & pods.valid
        ms = _lean_masked_score(pods, nodes, u, active, static_ok, res_on,
                                lean_plan)
        rowmax = jnp.max(ms, axis=1, keepdims=True)
        feasible_any = rowmax[:, 0] > guard
        wkey = jnp.where(active & feasible_any, rank, jnp.int32(P + 1))
        arank = _inverse_permutation(jnp.argsort(wkey))
        if P > window:
            # the bidder window only binds when more pods than window
            # slots exist — a trace-time fact, so small batches compile
            # it out entirely (arank < window is vacuous there)
            gate = active & feasible_any & (arank < window)
            ms = jnp.where(gate[:, None], ms, NEG)
            rowmax = jnp.max(ms, axis=1, keepdims=True)
        # tied bits are derived views over ms — never materialized as a
        # (P, N) matrix; the block gather re-derives its one (P, BL)
        # slice from ms directly
        N_ = ms.shape[1]
        BL = min(64, N_)
        row_live = rowmax > guard  # (P, 1)
        if N_ % BL:
            # non-bucketed node axis: materialize tied once and use the
            # shared fallback (see _blocked_pick)
            choice, _tc = _blocked_pick((ms >= rowmax) & row_live, arank)
        else:
            ms3 = ms.reshape(P, N_ // BL, BL)
            t3 = (ms3 >= rowmax[:, :, None]) & row_live[:, :, None]

            def gather_block(bidx):
                blk_ms = jnp.take_along_axis(
                    ms3, bidx[:, None, None], axis=1)[:, 0, :]  # (P, BL)
                return (blk_ms >= rowmax) & row_live

            choice, _tc = _blocked_pick_core(t3, gather_block, arank)
        feasible = jnp.take_along_axis(
            ms, choice[:, None], axis=1)[:, 0] > guard
        choice = jnp.where(feasible, choice, -1)
        # shared score-ordered per-node admission — the port/topology
        # guards the lean gates prove vacuous are simply absent
        accepted = _admit_scored(choice, rank, pods.req,
                                 nodes.allocatable - u.requested,
                                 per_node_cap, res_on)
        new_assigned = jnp.where(accepted, choice, assigned)
        u = _apply_batch(u, pods, jnp.where(accepted, choice, 0), accepted)
        return (new_assigned, u, jnp.any(accepted), rnd + 1, use_plan,
                sk_stats)

    def cond(carry):
        assigned, _, progressed, rnd, _, _ = carry
        return (progressed & (rnd < max_rounds)
                & jnp.any((assigned == -1) & pods.valid))

    init = (jnp.full((P,), -1, jnp.int32), usage_from_nodes(nodes),
            jnp.asarray(True), jnp.asarray(0, jnp.int32),
            jnp.asarray(False), jnp.full((2,), -1.0, jnp.float32))
    assigned, u, _, rounds, _, sk_stats = jax.lax.while_loop(
        cond, round_body, init)
    return assigned, u, rounds, sk_stats


@partial(jax.jit, static_argnames=("weights_key", "max_rounds", "per_node_cap",
                                   "use_sinkhorn", "skip_key", "no_ports",
                                   "no_pod_affinity", "no_spread",
                                   "fused_score", "auto_sinkhorn",
                                   "with_stats", "enabled_mask", "sk_tol",
                                   "potentials_out"))
def _batch_impl(pods, nodes, sel, topo, weights_key, max_rounds, per_node_cap,
                extra_mask, vol=None, static_vol=None, enabled_mask=None,
                extra_score=None, use_sinkhorn=False, skip_key=(),
                no_ports=False, no_pod_affinity=False, no_spread=False,
                fused_score=True, auto_sinkhorn=True, with_stats=False,
                sk_init=None, sk_tol=None, potentials_out=False):
    weights = dict(weights_key) if weights_key is not None else None
    # warm-started Sinkhorn (incremental solve, docs/perf.md): engage the
    # potential carry ONLY when a warm start or tolerance is requested —
    # the stock path keeps its per-round cold start bit for bit (each
    # round's plan solves from zeros exactly as before)
    sk_warm = (sk_init is not None) or (sk_tol is not None)
    # trace-time routing gate: no preference kernel live -> no possible
    # asymmetric tie cohort -> compile the router (and the plan branch)
    # out entirely
    auto_sinkhorn = (auto_sinkhorn and not use_sinkhorn
                     and not all(k in skip_key
                                 for k in _PREFERENCE_KERNELS))
    P = pods.req.shape[0]
    perm = queue_order(pods)
    rank = _inverse_permutation(perm)
    # ---- fused lean round path (trace-time routed) -----------------------
    # Constraint-light batches — no topology/volume/port coupling, no
    # extender/plugin mask or score, argmax tie-break, and a provably
    # exact lean scoring plan — run a round body that materializes ONE
    # (P, N) f32 matrix per round (the masked score) instead of the
    # general path's reasons + mask + per-kernel score temporaries, and
    # pick tied columns with the blocked selection instead of the (P, N)
    # cumsum + argmax. Placements are bit-identical to the general path
    # (identical mask/score arithmetic, rotation tie-break, and
    # score-ordered admission — pinned by the tests/test_fused_validate.py
    # parity suite): on the CPU headline shape this is the difference
    # between losing to and beating the sequential oracle (see
    # docs/perf.md readback budget).
    lean_plan = None
    if (topo is None and vol is None and static_vol is None
            and extra_mask is None and extra_score is None and no_ports
            and not use_sinkhorn and not auto_sinkhorn):
        lean_plan = _lean_score_plan(weights_key, skip_key)
    if lean_plan is not None:
        lr = _lean_rounds(pods, nodes, sel, rank, lean_plan, max_rounds,
                          per_node_cap, enabled_mask)
        if potentials_out:
            # the lean route never engages the transport plan (its gates
            # require use_sinkhorn and the auto-router off) — zero
            # potentials keep the return structure uniform
            return lr + ((jnp.zeros((P,), jnp.float32),
                          jnp.zeros((nodes.allocatable.shape[0],),
                                    jnp.float32)),)
        return lr
    # pods carrying host ports or attach-counted/conflict-checked volumes
    # are admitted at most one per node per round (conservative, exact):
    # their feasibility couples across same-round admissions to one node
    has_port = (
        jnp.sum(pods.port_wild_pp, axis=1) + jnp.sum(pods.port_spec_pp, axis=1)
    ) > 0
    if vol is not None:
        has_port = has_port | (
            jnp.sum(pods.vol_any_mh, axis=1)
            + jnp.sum(pods.pd_mh, axis=1)
            + jnp.sum(pods.csi_mh, axis=1)
            > 0
        )
    # usage-invariant predicate bits + selector program table, computed
    # ONCE against the base nodes: the round loop below re-evaluates only
    # the dynamic predicates (resources/ports/topology/volumes) against
    # the usage-updated node view
    hoisted = static_predicate_reasons(pods, nodes, sel)
    if vol is not None and static_vol is None:
        static_vol = static_volume_reasons(pods, nodes, sel, vol,
                                           prog=hoisted[1])
    # usage-invariant SCORING slice, once per batch: the static kernels'
    # full matrices + the static raw map phases (ops/priorities.py
    # hoist_priorities) — the round loop then pays only the per-round
    # mask-dependent normalizes and the genuinely dynamic kernels
    from kubernetes_tpu.ops.priorities import hoist_priorities

    hoisted_prio = hoist_priorities(pods, nodes, sel, weights, skip_key)
    if topo is not None and not (no_pod_affinity and no_spread):
        from kubernetes_tpu.ops.topology import sensitive_keys

        # (P, K) topology keys along which same-round co-admission into one
        # topology group could violate required anti-affinity / hard spread
        # (static over rounds; the per-round escape check is inside the
        # loop). Skipped when BOTH batch gates hold: a universe matcher
        # left by a long-gone affinity pod would otherwise mark clean pods
        # topology-sensitive and serialize their admissions per pair.
        sens = sensitive_keys(pods, topo, nodes.topo_pair_id.shape[1])
    else:
        sens = None

    def round_body(carry):
        assigned, u, _, rnd, use_plan, sk_stats, sk_u, sk_v = carry
        cur = nodes_with_usage(nodes, u)
        active = (assigned == -1) & pods.valid
        mask = (
            run_predicates(pods, cur, sel, topo, vol, static_vol,
                           enabled_mask, hoisted=hoisted,
                           no_ports=no_ports,
                           no_pod_affinity=no_pod_affinity,
                           no_spread=no_spread).mask
            & active[:, None]
        )
        if extra_mask is not None:
            mask = mask & extra_mask
        score = run_priorities(pods, cur, sel, mask, weights, topo,
                               skip=skip_key, hoisted=hoisted_prio,
                               fused=fused_score)
        if extra_score is not None:
            score = score + extra_score
        # ---- bidder window: the next K pods the serial loop would pop ----
        # Only the top K = N*per_node_cap active pods (by queue rank) that
        # have at least one feasible node may bid this round. Per-round
        # admissions are capped at K anyway, so this costs no throughput,
        # and it makes priority ordering a structural invariant: a pod can
        # be admitted only when fewer than K feasible higher-rank pods are
        # still waiting (the serial loop is the K=1 case). Pods with no
        # feasible node don't consume window slots — the serial loop pops
        # them, fails them, and moves on (they may become feasible later in
        # the batch as affinity targets land).
        feasible_any = jnp.any(mask, axis=1)
        wkey = jnp.where(active & feasible_any, rank, jnp.int32(P + 1))
        arank = _inverse_permutation(jnp.argsort(wkey))
        window = nodes.allocatable.shape[0] * per_node_cap
        # pre-window feasibility, kept for the auto-router: the window
        # admits only the next K bidders, so a tie-contention cohort
        # whose tail populations are still queued (exactly the
        # asymmetric-second-choice scenario) would be invisible to a
        # post-window detector in round 0
        mask_full = mask
        mask = mask & (active & feasible_any & (arank < window))[:, None]
        # deterministic tie-break spread — the batched analog of
        # selectHost's randomized round-robin among max-scoring nodes
        # (generic_scheduler.go:292). Without it, a uniform workload herds
        # every bidder onto the same lowest-index argmax node each round
        # and throughput collapses to per_node_cap pods/round. Each bidder
        # rotates among its EXACTLY-tied best nodes by its dense window
        # index, so the best-ranked bidder still takes the lowest node
        # index (deterministic) and equal-score cohorts fan out evenly.
        rowmax = jnp.max(jnp.where(mask, score, NEG), axis=1, keepdims=True)
        masked = jnp.where(mask, score - rowmax, NEG)

        def column_slots():
            # column capacity: how many ACTIVE pods could land on each node,
            # bounded per resource by the smallest active request — the pod
            # count column alone (~110/node) almost never binds, which would
            # degrade the plan to a per-row softmax with no pre-spreading
            from kubernetes_tpu.snapshot import RES_PODS

            free = jnp.maximum(nodes.allocatable - u.requested, 0.0)  # (N, R)
            min_req = jnp.min(
                jnp.where(
                    active[:, None] & (pods.req > 0), pods.req, jnp.inf
                ),
                axis=0,
            )  # (R,)
            per_res = jnp.where(
                jnp.isfinite(min_req),
                jnp.floor(free / jnp.maximum(min_req, 1e-30)),
                jnp.inf,
            )
            slots = jnp.min(per_res, axis=1)
            return jnp.where(jnp.isfinite(slots), slots, free[:, RES_PODS])

        def plan_tied(slots, pu, pv):
            # choose from the entropic-OT transport plan instead of the raw
            # per-pod argmax: the plan balances the whole batch against node
            # capacities, so contended pods pre-spread instead of colliding
            # (ops/sinkhorn.py; SURVEY.md §7.2 step 5). Convergence stats
            # (iterations-to-tolerance, final residual) ride the carry so
            # the driver can surface them per cycle without a host sync;
            # with_stats is a static key, so disabling telemetry compiles
            # the stats scan out entirely. Under sk_warm the potentials
            # ride the carry too: each round (and, via sk_init, each
            # CYCLE) warm-starts from the previous equilibrium, with the
            # sk_tol early-exit capping converged re-solves at one
            # verification iteration.
            from kubernetes_tpu.ops.sinkhorn import sinkhorn_plan

            res = sinkhorn_plan(masked, mask, slots,
                                with_stats=with_stats,
                                init=(pu, pv) if sk_warm else None,
                                tol=sk_tol, return_potentials=True)
            if with_stats:
                plan, stats, (pu2, pv2) = res
            else:
                plan, (pu2, pv2) = res
                stats = jnp.full((2,), -1.0, jnp.float32)
            # identical pods get identical plan rows (Sinkhorn scaling
            # preserves row identity), so the plan argmax needs the same
            # rotation tie-break as the raw-score branch or a uniform
            # cohort herds onto one node at per_node_cap pods/round
            pmasked = jnp.where(mask, plan, -1.0)
            prowmax = jnp.max(pmasked, axis=1, keepdims=True)
            return mask & (pmasked >= prowmax), stats, pu2, pv2

        argmax_tied = mask & (score >= rowmax)
        if use_sinkhorn:
            tied, sk_stats, sk_u, sk_v = plan_tied(column_slots(),
                                                   sk_u, sk_v)
        elif auto_sinkhorn:
            # ---- per-batch solver routing (VERDICT r4 item 5) ----
            # Decide ONCE, from round 0's structures: the plan wins only
            # on tie-contention with ASYMMETRIC second choices (pinned by
            # tests/test_sinkhorn.py::test_plan_beats_argmax_on_tied_
            # preferences); everything else takes the argmax path, so
            # the detection must separate (a) multi-way-tied bids, on
            # (b) oversubscribed columns, with (c) heterogeneous
            # runner-up gaps — each alone is argmax territory (uniform
            # cohorts rotate out; unique-best contention is what the
            # score-ordered admission already resolves).
            slots = column_slots()

            def detect():
                # evaluated over the PRE-window mask: the whole batch's
                # tie structure, not just the next K bidders (score is
                # computed before windowing, so this costs no extra
                # scoring — only the detection's own reductions, paid
                # once per batch inside the rnd==0 cond)
                rm = jnp.max(jnp.where(mask_full, score, NEG), axis=1,
                             keepdims=True)
                tied_f = mask_full & (score >= rm)
                tc0 = jnp.sum(tied_f, axis=1).astype(jnp.float32)
                share = tied_f.astype(jnp.float32) / jnp.maximum(
                    tc0, 1.0)[:, None]
                demand = jnp.sum(share, axis=0)  # (N,) intended tie mass
                over = demand > jnp.maximum(slots, 1e-9)
                cohort = (tc0 >= 2.0) & jnp.any(
                    tied_f & over[None, :], axis=1)
                alt = mask_full & ~tied_f
                r2 = jnp.max(jnp.where(alt, score, NEG), axis=1)
                gap = jnp.where(jnp.any(alt, axis=1),
                                rm[:, 0] - r2, 1e3)
                gmin = jnp.min(jnp.where(cohort, gap, jnp.inf))
                gmax = jnp.max(jnp.where(cohort, gap, -jnp.inf))
                return ((jnp.sum(cohort) >= AUTO_TIE_MIN_COHORT)
                        & (gmax - gmin >= AUTO_TIE_GAP_MARGIN))

            prev_decision = use_plan
            use_plan = jax.lax.cond(rnd == 0, detect,
                                    lambda: prev_decision)
            prev_stats = sk_stats
            prev_u, prev_v = sk_u, sk_v
            tied, sk_stats, sk_u, sk_v = jax.lax.cond(
                use_plan,
                lambda: plan_tied(slots, prev_u, prev_v),
                lambda: (argmax_tied, prev_stats, prev_u, prev_v))
        else:
            tied = argmax_tied
        # rotation pick via the blocked two-level selection (bit-identical
        # to the old full-width cumsum + argmax, which profiled at
        # 23 ms + 16 ms per round on XLA:CPU — see _blocked_pick)
        choice, _tcount = _blocked_pick(tied, arank)  # (P,)
        feasible = jnp.take_along_axis(mask, choice[:, None], axis=1)[:, 0]
        choice = jnp.where(feasible, choice, -1)

        # ---- per-node acceptance: highest-priority prefix that fits ----
        # (shared spelling: _admit_scored). The admission cap exists
        # because all pods in a round score against the SAME usage state:
        # unbounded admission herds the whole queue onto the current-best
        # node (usage-sensitive scores — LeastRequested, SelectorSpread —
        # only update between rounds). A small cap turns each round into
        # an auction step: nodes admit their best bidders, usage updates,
        # the rest re-bid. cap=1 approaches the serial loop's packing
        # quality; larger caps trade score fidelity for fewer rounds.
        from kubernetes_tpu.ops.predicates import BIT as _BIT

        res_on = enabled_mask is None or bool(
            enabled_mask & (1 << _BIT["PodFitsResources"]))

        def port_gate(order2, seg_starts):
            # one port-bearing pod per node per round (conservative, exact)
            hp_s = has_port[order2].astype(jnp.int32)
            hp_prefix = _segment_prefix(hp_s[:, None], seg_starts)[:, 0]
            return (hp_s == 0) | (hp_prefix == 0)

        accepted = _admit_scored(choice, rank, pods.req,
                                 nodes.allocatable - u.requested,
                                 per_node_cap, res_on,
                                 sorted_gate=port_gate)

        if sens is not None:
            from kubernetes_tpu.ops.topology import self_escape_active

            big = jnp.int32(2**30)

            def first_per_group(ok, gate, key):
                """Keep only the lowest-rank gated pod per group; ungated
                pods pass through."""
                gkey = jnp.where(gate, key, big)
                o = jnp.lexsort((rank, gkey))
                gk_s = gkey[o]
                starts = jnp.searchsorted(gk_s, gk_s, side="left")
                within = jnp.arange(P, dtype=jnp.int32) - starts
                keep_s = (gk_s == big) | (within == 0)
                keep = jnp.zeros((P,), bool).at[o].set(keep_s)
                return ok & (keep | ~gate)

            # one topo-sensitive pod per topology pair per round — the
            # batched guard for anti-affinity / hard-spread interactions
            # among same-round admissions (the serial loop never needs
            # this; in-batch it replaces per-pod cache updates)
            ok = accepted
            tpid = nodes.topo_pair_id
            for k in range(tpid.shape[1]):
                pair = tpid[jnp.clip(choice, 0, tpid.shape[0] - 1), k]
                gate = ok & (choice >= 0) & sens[:, k] & (pair >= 0)
                ok = first_per_group(ok, gate, pair)
            if not no_pod_affinity:
                # one self-match escapee per affinity program per round:
                # the second first-pod-of-a-group must wait and join the
                # first (affinity-only machinery)
                esc = self_escape_active(pods, cur, topo)
                gate_e = ok & (choice >= 0) & esc
                ok = first_per_group(ok, gate_e, pods.affprog_id)
            accepted = ok

        new_assigned = jnp.where(accepted, choice, assigned)
        u = _apply_batch(u, pods, jnp.where(accepted, choice, 0), accepted)
        progressed = jnp.any(accepted)
        return (new_assigned, u, progressed, rnd + 1, use_plan, sk_stats,
                sk_u, sk_v)

    def cond(carry):
        assigned, _, progressed, rnd = carry[:4]
        # three exits: a no-progress round (contention fixpoint), the
        # round budget, or — the hot-path case — NOTHING LEFT TO PLACE.
        # Without the third check every fully-placed batch pays one dead
        # full-matrix round just to discover it made no progress (the
        # uncontended headline's entire round 2); the (P,) reduction here
        # is noise next to the (P, N) passes it skips. Placements are
        # untouched: a round with zero active pods cannot change anything.
        return (progressed & (rnd < max_rounds)
                & jnp.any((assigned == -1) & pods.valid))

    # sk_stats: [-1, -1] = sinkhorn never engaged this solve; otherwise
    # the LAST round's [iterations-to-converge, final residual].
    # sk_u/sk_v: the potential carry — seeded from sk_init (a previous
    # cycle's equilibrium) under sk_warm, zeros otherwise.
    N_nodes = nodes.allocatable.shape[0]
    u0_init = (sk_init[0] if sk_warm and sk_init is not None
               else jnp.zeros((P,), jnp.float32))
    v0_init = (sk_init[1] if sk_warm and sk_init is not None
               else jnp.zeros((N_nodes,), jnp.float32))
    init = (jnp.full((P,), -1, jnp.int32), usage_from_nodes(nodes),
            jnp.asarray(True), jnp.asarray(0, jnp.int32),
            jnp.asarray(False), jnp.full((2,), -1.0, jnp.float32),
            u0_init.astype(jnp.float32), v0_init.astype(jnp.float32))
    assigned, u, _, rounds, _, sk_stats, sk_u, sk_v = jax.lax.while_loop(
        cond, round_body, init)
    if potentials_out:
        return assigned, u, rounds, sk_stats, (sk_u, sk_v)
    return assigned, u, rounds, sk_stats


def batch_assign(
    pods: DevicePods,
    nodes: DeviceNodes,
    sel: DeviceSelectors,
    weights: Optional[Dict[str, float]] = None,
    max_rounds: int = 256,
    per_node_cap: int = 1,
    topo=None,
    extra_mask: Optional[jnp.ndarray] = None,
    vol=None,
    static_vol: Optional[jnp.ndarray] = None,
    enabled_mask: Optional[int] = None,
    extra_score: Optional[jnp.ndarray] = None,
    use_sinkhorn: bool = False,
    skip_priorities=(),
    no_ports: bool = False,
    no_pod_affinity: bool = False,
    no_spread: bool = False,
    fused_score: bool = True,
    auto_sinkhorn: bool = True,
    fault_hook=None,
    fault_site: str = "solve:batch",
    stats_out: bool = False,
    sk_init=None,
    sk_tol: Optional[float] = None,
    potentials_out: bool = False,
) -> Tuple[jnp.ndarray, UsageState, jnp.ndarray]:
    """Fast batched solver. Returns (assigned row per pod or -1, final
    usage, rounds executed). ``per_node_cap`` bounds admissions per node per
    round (see _batch_impl); with P pending pods and N nodes expect about
    ceil(P / (N * cap)) rounds on uniform workloads. ``extra_mask`` as in
    :func:`greedy_assign`.

    ``stats_out`` appends a 4th element: a (2,) f32 device array
    [sinkhorn iterations-to-converge, final residual] from the last
    round that ran the transport plan, or [-1, -1] when the plan never
    engaged (argmax path). Stays a device value — the observability
    layer reads it back once per cycle at the host boundary.

    ``fused_score`` (feature flag, default on): collapse the two hoisted
    normalize-reduce scoring kernels into one single-output pass per
    round (ops/priorities.py _fused_pair_normalize). Only engages when
    the regrouped accumulation is provably exact (all-stock kernels,
    integer weights) — bit-identical placements either way, pinned by
    tests/test_priorities.py.

    ``extra_mask=None`` is a TRACE-TIME fact (not substituted with an
    all-true matrix): clean batches route to the fused lean round path
    (see _batch_impl) whose per-round device work — and therefore the
    d2h readback wait at the host boundary — is several times smaller.

    Warm-started Sinkhorn (incremental solve): ``sk_init`` seeds the
    transport-plan potentials from a previous solve's equilibrium (a
    ``(u0, v0)`` pair), ``sk_tol`` switches the scaling to the
    tolerance-gated early-exit loop, and ``potentials_out`` appends the
    final ``(u, v)`` pair to the return so the caller can carry it into
    the next cycle. All three leave the stock cold-start path untouched
    when unset."""
    if fused_score:
        # resolve the backend policy HERE so it becomes part of the jit
        # key: use_pallas() reads env + backend at call time, and a
        # policy flip must recompile, not hit a stale cache entry
        from kubernetes_tpu.ops.fused_score import use_pallas

        fused_score = use_pallas()
    args, kw = _batch_impl_call(
        pods, nodes, sel, weights, max_rounds, per_node_cap, topo,
        extra_mask, vol, static_vol, enabled_mask, extra_score,
        use_sinkhorn, skip_priorities, no_ports, no_pod_affinity,
        no_spread, fused_score, auto_sinkhorn, stats_out,
        sk_init, sk_tol, potentials_out)
    out = _batch_impl(*args, **kw)
    potentials = out[4] if potentials_out else None
    assigned, u, rounds, sk_stats = out[:4]
    if fault_hook is not None:
        # the fault-injection seam (see greedy_assign): the hook stands
        # where an out-of-process solver's response would be decoded
        assigned, u, rounds = fault_hook(fault_site, assigned, u, rounds,
                                         nodes.allocatable.shape[0])
    ret = (assigned, u, rounds)
    if stats_out:
        ret = ret + (sk_stats,)
    if potentials_out:
        ret = ret + (potentials,)
    return ret


def _batch_impl_call(pods, nodes, sel, weights, max_rounds, per_node_cap,
                     topo, extra_mask, vol, static_vol, enabled_mask,
                     extra_score, use_sinkhorn, skip_priorities, no_ports,
                     no_pod_affinity, no_spread, fused_score, auto_sinkhorn,
                     stats_out, sk_init=None, sk_tol=None,
                     potentials_out=False):
    """THE one spelling of the ``_batch_impl`` invocation — returns
    ``(args, kwargs)`` for both the live call (:func:`batch_assign`)
    and the AOT lowering (:func:`solve_cost_analysis`), so the cost
    capture can never silently lower a different program than the one
    live cycles run (a new kwarg added in one place and missed in the
    other would skew model_efficiency without failing anything)."""
    key = tuple(sorted(weights.items())) if weights is not None else None
    args = (pods, nodes, sel, topo, key, max_rounds, per_node_cap,
            extra_mask, vol, static_vol, enabled_mask, extra_score,
            use_sinkhorn)
    kw = dict(skip_key=tuple(skip_priorities), no_ports=no_ports,
              no_pod_affinity=no_pod_affinity, no_spread=no_spread,
              fused_score=fused_score, auto_sinkhorn=auto_sinkhorn,
              with_stats=stats_out, sk_init=sk_init, sk_tol=sk_tol,
              potentials_out=potentials_out)
    return args, kw


def solve_cost_analysis(
    pods: DevicePods,
    nodes: DeviceNodes,
    sel: DeviceSelectors,
    weights: Optional[Dict[str, float]] = None,
    *,
    max_rounds: int = 256,
    per_node_cap: int = 1,
    topo=None,
    vol=None,
    static_vol: Optional[jnp.ndarray] = None,
    enabled_mask: Optional[int] = None,
    extra_score: Optional[jnp.ndarray] = None,
    use_sinkhorn: bool = False,
    skip_priorities=(),
    no_ports: bool = False,
    no_pod_affinity: bool = False,
    no_spread: bool = False,
    stats_out: bool = False,
) -> Optional[dict]:
    """XLA cost analysis of the dense batch solve at this exact
    signature — the perf ledger's model-side capture (obs/ledger.py):
    warmup lowers the SAME jitted program :func:`batch_assign` runs
    (identical static keys) and reads the compiled executable's
    ``cost_analysis()`` flops / bytes-accessed. Best-effort by
    contract: returns ``{"flops": ..., "bytes_accessed": ...}`` or
    ``None`` when the backend (or this jax version) declines AOT
    analysis — warmup must never fail for its accountant. Host-side
    AOT only; never on the cycle path."""
    from kubernetes_tpu.ops.fused_score import use_pallas

    from kubernetes_tpu.obs.ledger import capture_cost_analysis

    args, kw = _batch_impl_call(
        pods, nodes, sel, weights, max_rounds, per_node_cap, topo,
        None, vol, static_vol, enabled_mask, extra_score,
        use_sinkhorn, skip_priorities, no_ports, no_pod_affinity,
        no_spread, use_pallas(), True, stats_out)
    return capture_cost_analysis(lambda: _batch_impl.lower(*args, **kw))


def solve_memory_analysis(
    pods: DevicePods,
    nodes: DeviceNodes,
    sel: DeviceSelectors,
    weights: Optional[Dict[str, float]] = None,
    *,
    max_rounds: int = 256,
    per_node_cap: int = 1,
    topo=None,
    vol=None,
    static_vol: Optional[jnp.ndarray] = None,
    enabled_mask: Optional[int] = None,
    extra_score: Optional[jnp.ndarray] = None,
    use_sinkhorn: bool = False,
    skip_priorities=(),
    no_ports: bool = False,
    no_pod_affinity: bool = False,
    no_spread: bool = False,
    stats_out: bool = False,
) -> Optional[dict]:
    """XLA memory analysis of the dense batch solve at this exact
    signature — the memory ledger's preflight capture
    (obs/memledger.py): warmup lowers the SAME jitted program
    :func:`batch_assign` runs (identical static keys, via
    :func:`_batch_impl_call` like :func:`solve_cost_analysis`) and
    reads the compiled executable's ``memory_analysis()``
    argument/output/temp bytes. Best-effort by contract: returns the
    byte dict or ``None`` when the backend declines — warmup must
    never fail for its accountant. Host-side AOT only; never on the
    cycle path (``memory_analysis`` exists only on the COMPILED
    stage, so each capture pays one AOT compile at warmup)."""
    from kubernetes_tpu.ops.fused_score import use_pallas

    from kubernetes_tpu.obs.memledger import capture_memory_analysis

    args, kw = _batch_impl_call(
        pods, nodes, sel, weights, max_rounds, per_node_cap, topo,
        None, vol, static_vol, enabled_mask, extra_score,
        use_sinkhorn, skip_priorities, no_ports, no_pod_affinity,
        no_spread, use_pallas(), True, stats_out)
    return capture_memory_analysis(
        lambda: _batch_impl.lower(*args, **kw))


# graftlint: disable-scope=R2,R7 -- the deliberate host boundary: trust-but-
# verify reads the solver's claimed result back ONCE per cycle to check it
# before any pod binds; cheap O(P*R + N*R) numpy by design (see docstring)
def validate_solution(
    assigned, usage: UsageState, pods: DevicePods, nodes: DeviceNodes,
    enabled_mask: Optional[int] = None,
) -> Tuple[bool, str]:
    """Trust-but-verify for a solver result before any pod is assumed —
    the check that keeps a lying/corrupted solver (or a stale-snapshot
    race) from binding an infeasible pod. Returns (ok, reason) with
    ``reason`` one of shape | dtype | range | invalid-node | finiteness
    | capacity.

    Deliberately cheap (O(P·R + N·R) host numpy): shape and index-range
    sanity, claimed-usage finiteness, and a full per-node capacity
    recomputation from the assignment itself (never trusting the
    solver's usage for feasibility). Capacity is only enforced when the
    PodFitsResources predicate is (the Policy-bypass rule the solvers
    themselves follow), and only blames nodes that were within
    allocatable BEFORE this batch — force-bound overcommit from the
    cache is not the solver's lie."""
    import numpy as np

    from kubernetes_tpu.ops.predicates import BIT

    P = pods.req.shape[0]
    try:
        a = np.asarray(assigned)
    except Exception:
        return False, "dtype"
    if a.ndim != 1 or a.shape[0] < P:
        return False, "shape"
    a = a[:P]
    if not np.issubdtype(a.dtype, np.integer):
        if not np.all(np.isfinite(a)):
            return False, "finiteness"
        if np.any(a != np.floor(a)):
            return False, "dtype"
        a = a.astype(np.int64)
    valid = np.asarray(pods.valid)
    nvalid = np.asarray(nodes.valid)
    N = nvalid.shape[0]
    if np.any(valid & ((a < -1) | (a >= N))):
        return False, "range"
    sel = valid & (a >= 0)
    if np.any(sel & ~nvalid[np.clip(a, 0, N - 1)]):
        return False, "invalid-node"
    if not bool(np.all(np.isfinite(np.asarray(usage.requested)))):
        return False, "finiteness"
    res_on = enabled_mask is None or bool(
        enabled_mask & (1 << BIT["PodFitsResources"])
    )
    if res_on and np.any(sel):
        req = np.asarray(pods.req)
        base = np.asarray(nodes.requested)
        alloc = np.asarray(nodes.allocatable)
        add = np.zeros_like(base)
        np.add.at(add, a[sel], req[sel])
        # relative tolerance: float32 scatter-add drift scales with the
        # magnitude (memory columns are bytes), so an absolute epsilon
        # would false-positive on honest results
        tol = 1e-5 * np.maximum(alloc, 1.0) + 1e-6
        pre_ok = base <= alloc + tol
        over = (base + add > alloc + tol) & nvalid[:, None] & (add > 0)
        if np.any(over & pre_ok):
            return False, "capacity"
    return True, ""


#: device_validate verdict-code vocabulary, in the same precedence order
#: the host checker reports (index 0 = ok). Host-side decode:
#: ``VALIDATE_REASONS[int(code)]``.
VALIDATE_REASONS = ("", "shape", "dtype", "range", "invalid-node",
                    "finiteness", "capacity")


@partial(jax.jit, static_argnames=("enabled_mask",))
def _device_validate_impl(assigned, usage_requested, pods, nodes,
                          enabled_mask=None):
    """Device half of :func:`device_validate`: every check
    :func:`validate_solution` runs, as one jitted reduction over the
    assignment — the verdict stays a pair of device scalars until the
    driver's single end-of-solve readback."""
    from kubernetes_tpu.ops.predicates import BIT

    P = pods.req.shape[0]
    valid = pods.valid
    nvalid = nodes.valid
    N = nvalid.shape[0]
    a = assigned[:P]
    if not jnp.issubdtype(a.dtype, jnp.integer):
        # a lying solver returning floats: finiteness first, then the
        # integer-valuedness check, then proceed on the floored values —
        # the same precedence the host checker applies
        fin_a_bad = ~jnp.all(jnp.isfinite(a))
        dtype_bad = jnp.any(jnp.where(jnp.isfinite(a), a != jnp.floor(a),
                                      False))
        a = jnp.where(jnp.isfinite(a), a, -2.0).astype(jnp.int32)
    else:
        fin_a_bad = jnp.asarray(False)
        dtype_bad = jnp.asarray(False)
        a = a.astype(jnp.int32)
    range_bad = jnp.any(valid & ((a < -1) | (a >= N)))
    sel = valid & (a >= 0)
    ac = jnp.clip(a, 0, N - 1)
    invalid_node = jnp.any(sel & ~nvalid[ac])
    fin_bad = ~jnp.all(jnp.isfinite(usage_requested))
    res_on = enabled_mask is None or bool(
        enabled_mask & (1 << BIT["PodFitsResources"]))
    if res_on:
        req = pods.req
        base = nodes.requested
        alloc = nodes.allocatable
        w = sel.astype(req.dtype)[:, None]
        add = jnp.zeros_like(base).at[jnp.where(sel, ac, 0)].add(req * w)
        tol = 1e-5 * jnp.maximum(alloc, 1.0) + 1e-6
        pre_ok = base <= alloc + tol
        over = (base + add > alloc + tol) & nvalid[:, None] & (add > 0)
        cap_bad = jnp.any(over & pre_ok)
    else:
        cap_bad = jnp.asarray(False)
    code = jnp.where(
        fin_a_bad, 5, jnp.where(
            dtype_bad, 2, jnp.where(
                range_bad, 3, jnp.where(
                    invalid_node, 4, jnp.where(
                        fin_bad, 5, jnp.where(cap_bad, 6, 0))))))
    return code.astype(jnp.int32), jnp.sum(sel, dtype=jnp.int32)


def device_validate(assigned, usage: UsageState, pods: DevicePods,
                    nodes: DeviceNodes,
                    enabled_mask: Optional[int] = None):
    """Fused on-device twin of :func:`validate_solution` — the readback
    killer: instead of materializing the assignment, the claimed usage,
    and four node/pod tables on host to re-check capacity (six device
    syncs per cycle), the whole verdict is computed on device and rides
    the driver's ONE end-of-solve readback as two int32 scalars
    ``(code, valid_count)``; decode with :data:`VALIDATE_REASONS`.

    Semantics are bit-matched to the host checker (pinned by the
    randomized parity suite in tests/test_fused_validate.py) with two
    host-visible shortcuts kept on host because they read metadata only:
    a result that is not array-like at all, or whose shape cannot cover
    the batch, never reaches the device. The one caveat: the capacity
    recomputation's f32 scatter-add may associate differently than the
    host's sequential ``np.add.at``, so verdicts within one float ulp of
    the relative tolerance boundary can differ — the host checker stays
    the trust floor (``robustness.host_validate``) and the parity oracle.

    Like the host checker this never trusts the solver's claimed usage
    for feasibility — capacity is recomputed from the assignment itself;
    the claimed usage is only checked for finiteness."""
    shape = getattr(assigned, "shape", None)
    dtype = getattr(assigned, "dtype", None)
    if shape is None or dtype is None or len(shape) != 1 \
            or shape[0] < pods.req.shape[0]:
        return None  # host verdict: (False, "shape") — caller falls back
    return _device_validate_impl(assigned, usage.requested, pods, nodes,
                                 enabled_mask=enabled_mask)
