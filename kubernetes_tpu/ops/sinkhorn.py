"""Sinkhorn optimal-transport scoring for batched/gang assignment
(SURVEY.md §2.4/§7.2 step 5: "Sinkhorn optimal-transport / auction
algorithm for gang & global assignment (PodGroup config)").

The round solver's per-pod argmax is myopic: every pod bids its best node
regardless of global contention. The entropic-OT plan instead balances the
whole batch against node capacities — pod p's row of the transport plan
already discounts nodes other pods need more — so argmax-of-plan choices
collide far less and pack gangs coherently.

Formulation: unbalanced entropic OT with
  - row marginals: each schedulable pod ships (at most) mass 1,
  - column marginals: node j receives AT MOST ``capacity_j`` (inequality —
    the column scaling only ever scales *down*, the standard unbalanced
    Sinkhorn treatment of capacity upper bounds),
  - kernel K = exp(score/eps) on feasible (pod, node) pairs.

Iterations run in log space for stability. Two implementations: pure jnp
(`_scale_jnp`, differentiable, any backend) and a Pallas TPU kernel pair
(`_scale_pallas`) that tiles the (P, N) log-kernel through VMEM — row and
column logsumexp reductions each fused into one pass per iteration
(pallas_guide.md patterns; selected via ``use_pallas``/KTPU_PALLAS).

Measured honestly (rounds 3-4, CPU): on margin-ORDERED workloads —
uniform gangs, scarce capacity (96-100% demand), heterogeneous
big/small-pod gangs, image-locality margins — the OT plan produces
IDENTICAL placements to the argmax rounds at 4-5x the solve cost: the
round solver's score-ordered per-node admission already reaches the OT
outcome whenever the contended nodes' scores are strictly ordered.
Argmax rounds therefore stay the default.

Where the plan DOES win (round 4, scripts/sinkhorn_quality.py): TOP-SCORE
TIES with asymmetric second choices — two populations tie on scarce "hot"
nodes but one's fallback is nearly free (hot=10/cold=9) while the other's
craters (hot=10/cold=0). Argmax admission sees identical bids, so
tie-breaks hand hot capacity to whichever population is favored by
ordering (adversarial order: 0/32 steep pods on hot, 2048 aggregate
affinity points); the transport plan prices hot-column contention so the
flat rows keep mass on the plentiful near-equal cold columns (16/32,
2192 points; optimum 2336). Opportunity cost is exactly the term per-pod
argmax cannot represent — enable ``use_sinkhorn`` for workloads with
tied contended preferences (pinned by
tests/test_sinkhorn.py::test_plan_beats_argmax_on_tied_preferences)."""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _row_lse(logk: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    return jax.scipy.special.logsumexp(logk + v[None, :], axis=1)


def _col_lse(logk: jnp.ndarray, u: jnp.ndarray) -> jnp.ndarray:
    return jax.scipy.special.logsumexp(logk + u[:, None], axis=0)


#: convergence tolerance for the telemetry scan: max |u - u_prev| under
#: this counts the iteration as converged (log-domain, so ~relative)
STATS_TOL = 1e-3


def _stats_scan(step, u0, v0, iters, tol=STATS_TOL):
    """Run ``step`` for ``iters`` iterations while tracking convergence:
    returns (u, v, stats) with stats = [first iteration whose max row-
    potential delta dropped under ``tol`` (or ``iters`` if never),
    final delta]. Same math as the plain scan — the extra carry is a
    scalar counter and a (P,)-sized masked subtraction per iteration."""

    def body(carry, i):
        u, v, conv = carry
        u2, v2 = step(u, v)
        finite = (u2 > NEG_INF / 2) & (u > NEG_INF / 2)
        delta = jnp.max(jnp.where(finite, jnp.abs(u2 - u), 0.0))
        conv = jnp.where((conv < 0) & (delta < tol), i + 1, conv)
        return (u2, v2, conv), delta

    (u, v, conv), deltas = jax.lax.scan(
        body, (u0, v0, jnp.asarray(-1, jnp.int32)),
        jnp.arange(iters, dtype=jnp.int32))
    iters_used = jnp.where(conv < 0, iters, conv).astype(jnp.float32)
    return u, v, jnp.stack([iters_used, deltas[-1].astype(jnp.float32)])


def _tol_scan(step, u0, v0, iters, tol):
    """Tolerance-gated scaling loop — the WARM-START companion of
    :func:`_stats_scan`: run ``step`` until the max row-potential delta
    drops under ``tol`` (or the ``iters`` budget runs out). A warm start
    whose residual is already under tolerance exits after ONE
    verification iteration instead of paying the full budget — the
    incremental-solve early-exit (docs/perf.md). Returns (u, v, stats)
    with the same [iterations-used, final-delta] stats vector."""

    def cond(carry):
        _u, _v, i, delta = carry
        return (i < iters) & (delta >= tol)

    def body(carry):
        u, v, i, _ = carry
        u2, v2 = step(u, v)
        finite = (u2 > NEG_INF / 2) & (u > NEG_INF / 2)
        delta = jnp.max(jnp.where(finite, jnp.abs(u2 - u), 0.0))
        return (u2, v2, i + 1, delta)

    u, v, i, delta = jax.lax.while_loop(
        cond, body,
        (u0, v0, jnp.asarray(0, jnp.int32),
         jnp.asarray(jnp.inf, jnp.float32)))
    return u, v, jnp.stack([i.astype(jnp.float32),
                            delta.astype(jnp.float32)])


def _scale_jnp(logk, log_r, log_c, iters, with_stats=False, u0=None,
               v0=None, tol=None):
    """Alternating log-domain scaling; columns clipped at 0 (inequality).
    Returns (u, v, stats) — stats is None unless ``with_stats`` or
    ``tol`` is set. ``u0``/``v0`` warm-start the potentials (a previous
    solve's equilibrium — Sinkhorn scaling converges from any start, so
    warm starts change only the iteration count, not the fixpoint);
    ``tol`` switches to the tolerance-gated loop (:func:`_tol_scan`)."""

    def step(u, v):
        u = log_r - _row_lse(logk, v)
        u = jnp.where(jnp.isfinite(u), u, NEG_INF)
        v = jnp.minimum(log_c - _col_lse(logk, u), 0.0)
        v = jnp.where(jnp.isfinite(v), v, 0.0)
        return u, v

    P, N = logk.shape
    if u0 is None:
        u0 = jnp.zeros((P,))
    if v0 is None:
        v0 = jnp.zeros((N,))
    if tol is not None:
        return _tol_scan(step, u0, v0, iters, tol)
    if with_stats:
        return _stats_scan(step, u0, v0, iters)
    (u, v), _ = jax.lax.scan(
        lambda carry, _: (step(*carry), None), (u0, v0), None, length=iters
    )
    return u, v, None


# ---------------------------------------------------------------------------
# Pallas TPU kernels: tiled row/column logsumexp scaling passes
# ---------------------------------------------------------------------------


def _u_kernel(logk_ref, v_ref, logr_ref, u_ref):
    """One row-scaling pass over a (Bp, N) tile: u = log_r - lse(logk+v).

    All vector operands are (1, X) row vectors: Mosaic requires the
    minor-most dim to follow the (8, 128) f32 tiling, and 1-D blocks get
    a T(256)-style layout that conflicts with XLA's T(1024) vector layout
    (the round-2 Mosaic verification failure)."""
    x = logk_ref[:] + v_ref[:]  # (Bp, N) + (1, N)
    m = jnp.max(x, axis=1, keepdims=True)
    m = jnp.maximum(m, NEG_INF)  # all-masked rows stay finite
    lse = jnp.log(jnp.sum(jnp.exp(x - m), axis=1, keepdims=True) + 1e-30) + m
    u = logr_ref[0, :] - lse[:, 0]
    u_ref[0, :] = jnp.where(u > NEG_INF / 2, u, NEG_INF)


def _v_kernel(logk_ref, u_ref, logc_ref, v_ref):
    """One column-scaling pass over a (P, Bn) tile, clipped at 0."""
    x = logk_ref[:] + u_ref[0, :][:, None]  # (P, Bn)
    m = jnp.max(x, axis=0, keepdims=True)
    m = jnp.maximum(m, NEG_INF)
    lse = jnp.log(jnp.sum(jnp.exp(x - m), axis=0, keepdims=True) + 1e-30) + m
    v = jnp.minimum(logc_ref[0, :] - lse[0, :], 0.0)
    v_ref[0, :] = jnp.where(v > NEG_INF / 2, v, 0.0)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


BLOCK_P, BLOCK_N = 256, 512

# Per-slab VMEM budget for the (bp, N)/(P, bn) logk tiles. Mosaic
# double-buffers each input block, and the strictest compile path in play
# (the axon tunnel's chipless AOT helper) enforces a 16 MiB scoped-vmem
# stack limit — at the gang shape (8192x5120) a (P, bn=512) slab is
# 16 MiB -> 32 MiB double-buffered and the compile dies with a scoped
# vmem OOM even though the on-device JIT path accepts it. 4 MiB per slab
# (8 MiB buffered) keeps both kernels comfortably inside every path.
VMEM_SLAB_BUDGET = 4 * 1024 * 1024


def _block_shapes(P0: int, N0: int, block_p: int = BLOCK_P,
                  block_n: int = BLOCK_N) -> Tuple[int, int, int, int]:
    """(bp, bn, padded P, padded N) — ONE place for the block/padding
    arithmetic so the compile probe and the real call can never diverge.
    Block dims double as lane dims of the (1, bp)/(1, bn) vector tiles, so
    both must be multiples of 128 (f32 lane tiling); bp is also the
    sublane dim of the (bp, N) tile (multiple of 8 — implied by 128).
    Blocks shrink (floor 128) until each kernel's logk slab fits
    VMEM_SLAB_BUDGET; shapes where even the 128-floor slab exceeds it
    (P or N ~> 8k on the other axis) fail the compile probe and take the
    jnp path."""
    bp = min(block_p, _round_up(P0, 128))
    bn = min(block_n, _round_up(N0, 128))
    # Fixed-point shrink: each check uses the FINAL padded extent of the
    # other axis, so a (bp, bn, P, N) result re-fed through this function
    # (as the compile probe does via _scale_pallas) reproduces itself —
    # the probe always exercises the exact kernel config of the real call.
    while True:
        P, N = _round_up(P0, bp), _round_up(N0, bn)
        if bp > 128 and bp * N * 4 > VMEM_SLAB_BUDGET:
            bp -= 128
            continue
        if bn > 128 and P * bn * 4 > VMEM_SLAB_BUDGET:
            bn -= 128
            continue
        return bp, bn, P, N


def _scale_pallas(logk, log_r, log_c, iters, block_p=BLOCK_P, block_n=BLOCK_N,
                  interpret=False, with_stats=False, u0=None, v0=None):
    from jax.experimental import pallas as pl

    P0, N0 = logk.shape
    # pad to block multiples (grid uses exact division); padded rows ship
    # nothing (log_r = -inf) and padded columns accept nothing (their
    # kernel column is -inf so their v never matters)
    bp, bn, P, N = _block_shapes(P0, N0, block_p, block_n)
    if (P, N) != (P0, N0):
        logk = jnp.pad(logk, ((0, P - P0), (0, N - N0)),
                       constant_values=NEG_INF)
        log_r = jnp.pad(log_r, (0, P - P0), constant_values=NEG_INF)
        log_c = jnp.pad(log_c, (0, N - N0), constant_values=NEG_INF)
    u_call = pl.pallas_call(
        _u_kernel,
        grid=(P // bp,),
        in_specs=[
            pl.BlockSpec((bp, N), lambda i: (i, 0)),
            pl.BlockSpec((1, N), lambda i: (0, 0)),
            pl.BlockSpec((1, bp), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((1, bp), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, P), logk.dtype),
        interpret=interpret,
    )
    v_call = pl.pallas_call(
        _v_kernel,
        grid=(N // bn,),
        in_specs=[
            pl.BlockSpec((P, bn), lambda j: (0, j)),
            pl.BlockSpec((1, P), lambda j: (0, 0)),
            pl.BlockSpec((1, bn), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((1, bn), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((1, N), logk.dtype),
        interpret=interpret,
    )
    log_r2 = log_r[None, :]
    log_c2 = log_c[None, :]

    def step(u, v):
        u = u_call(logk, v, log_r2)
        v = v_call(logk, u, log_c2)
        return u, v

    # warm-start potentials pad with 0 (padded rows ship nothing — their
    # first u pass lands on NEG_INF regardless of the start)
    u0 = (jnp.zeros((1, P), logk.dtype) if u0 is None
          else jnp.pad(u0, (0, P - u0.shape[0]))[None, :].astype(logk.dtype))
    v0 = (jnp.zeros((1, N), logk.dtype) if v0 is None
          else jnp.pad(v0, (0, N - v0.shape[0]))[None, :].astype(logk.dtype))
    if with_stats:
        u, v, stats = _stats_scan(step, u0, v0, iters)
        return u[0, :P0], v[0, :N0], stats
    (u, v), _ = jax.lax.scan(
        lambda carry, _: (step(*carry), None), (u0, v0), None, length=iters,
    )
    return u[0, :P0], v[0, :N0], None


@functools.lru_cache(maxsize=64)
def _pallas_compiles(bp: int, bn: int, P: int, N: int) -> bool:
    """One-time compile probe at the exact padded shape AND block config:
    Mosaic layout/vmem verification happens at compile time inside
    whatever jit wraps the solver, where a try/except around the traced
    call can't catch it. A failed probe downgrades to `_scale_jnp` (same
    math, any backend) instead of killing the whole gang variant
    (round-2 weak #9). Passing (bp, bn) pins the probed kernel to the
    real call's config — `_block_shapes` is a fixed point on padded
    shapes, so `_scale_pallas` inside recomputes the identical tiling."""
    try:
        # graftlint: disable=R3 -- one-time compile probe, memoized by the
        # lru_cache above: the wrapper is built once per (block, shape) key
        u, v, _ = jax.jit(functools.partial(
            _scale_pallas, iters=1, block_p=bp, block_n=bn,
            with_stats=False))(
            jnp.zeros((P, N), jnp.float32),
            jnp.zeros((P,), jnp.float32),
            jnp.zeros((N,), jnp.float32),
        )
        jax.block_until_ready((u, v))
        return True
    except Exception:
        return False


def use_pallas() -> bool:
    """Pallas path policy: on by default on real TPU, opt-in elsewhere
    (KTPU_PALLAS=1 forces interpret-mode execution for testing)."""
    env = os.environ.get("KTPU_PALLAS", "")
    if env == "0":
        return False
    if env == "1":
        return True
    return jax.default_backend() == "tpu"


def sinkhorn_plan(
    score: jnp.ndarray,
    mask: jnp.ndarray,
    capacity: jnp.ndarray,
    eps: float = 0.5,
    iters: int = 25,
    pallas: Optional[bool] = None,
    interpret: Optional[bool] = None,
    with_stats: bool = False,
    init: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
    tol: Optional[float] = None,
    return_potentials: bool = False,
) -> jnp.ndarray:
    """Transport plan (P, N): plan[p, j] ≈ how much of pod p's unit demand
    node j serves at equilibrium. Row sums <= 1 (== 1 when the pod fits
    anywhere with spare capacity); column sums <= capacity + O(tolerance).

    ``with_stats`` additionally returns a (2,) f32 device array
    [iterations-to-converge (== ``iters`` when the tolerance was never
    reached), final max row-potential delta] — the per-solve convergence
    telemetry the observability layer surfaces (obs/core.py reads it back
    once per cycle at the host boundary). Same scaling math either way.

    Warm start (the incremental-solve carry, docs/perf.md): ``init`` is
    a ``(u0, v0)`` potential pair from a previous solve — scaling
    converges from any start to the same fixpoint, so a warm start
    changes only the iteration count. ``tol`` switches to the
    tolerance-gated loop: iterate until the max row-potential delta
    drops under ``tol`` (a warm start already under it exits after one
    verification iteration). The tolerance loop runs the jnp scaling on
    every backend (the Pallas kernels keep their fixed-iteration scans
    — a data-dependent trip count would defeat their pipelining).
    ``return_potentials`` appends the final ``(u, v)`` pair so the
    caller can carry it into the next solve.
    """
    score = score.astype(jnp.float32)
    row_ok = jnp.any(mask, axis=1)
    logk = jnp.where(mask, score / eps, NEG_INF)
    log_r = jnp.where(row_ok, 0.0, NEG_INF)  # demand 1 per schedulable pod
    log_c = jnp.where(capacity > 0, jnp.log(jnp.maximum(capacity, 1e-30)), NEG_INF)
    u0 = v0 = None
    if init is not None:
        u0, v0 = init
        # sanitize a foreign start: non-finite rows restart from zero
        # (a NEG_INF row potential from a previously-infeasible pod
        # would wedge its row at zero mass forever)
        u0 = jnp.where(jnp.isfinite(u0) & (u0 > NEG_INF / 2), u0, 0.0)
        v0 = jnp.where(jnp.isfinite(v0) & (v0 > NEG_INF / 2), v0, 0.0)
    if pallas is None:
        pallas = use_pallas()
    if tol is not None:
        pallas = False  # the tolerance loop is jnp-only (see docstring)
    if pallas:
        interp = (jax.default_backend() != "tpu") if interpret is None else interpret
        if not interp:
            # compiled mode: probe the exact padded shape first; fall back
            # to the jnp path on Mosaic failure instead of propagating a
            # compile error out of the caller's jit
            pallas = _pallas_compiles(*_block_shapes(*logk.shape))
    if pallas:
        u, v, stats = _scale_pallas(logk, log_r, log_c, iters,
                                    interpret=interp, with_stats=with_stats,
                                    u0=u0, v0=v0)
    else:
        u, v, stats = _scale_jnp(logk, log_r, log_c, iters,
                                 with_stats=with_stats, u0=u0, v0=v0,
                                 tol=tol)
    plan = jnp.exp(
        jnp.clip(logk + u[:, None] + v[None, :], NEG_INF, 30.0)
    )
    plan = jnp.where(mask, plan, 0.0)
    out = (plan,)
    if with_stats:
        out = out + (stats,)
    if return_potentials:
        out = out + ((u, v),)
    return out if len(out) > 1 else plan
