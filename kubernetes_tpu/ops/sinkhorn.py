"""Sinkhorn optimal-transport scoring for batched/gang assignment
(SURVEY.md §2.4/§7.2 step 5: "Sinkhorn optimal-transport / auction
algorithm for gang & global assignment (PodGroup config)").

The round solver's per-pod argmax is myopic: every pod bids its best node
regardless of global contention. The entropic-OT plan instead balances the
whole batch against node capacities — pod p's row of the transport plan
already discounts nodes other pods need more — so argmax-of-plan choices
collide far less and pack gangs coherently.

Formulation: unbalanced entropic OT with
  - row marginals: each schedulable pod ships (at most) mass 1,
  - column marginals: node j receives AT MOST ``capacity_j`` (inequality —
    the column scaling only ever scales *down*, the standard unbalanced
    Sinkhorn treatment of capacity upper bounds),
  - kernel K = exp(score/eps) on feasible (pod, node) pairs.

Iterations run in log space for stability. Two implementations: pure jnp
(`_scale_jnp`, differentiable, any backend) and a Pallas TPU kernel pair
(`_scale_pallas`) that tiles the (P, N) log-kernel through VMEM — row and
column logsumexp reductions each fused into one pass per iteration
(pallas_guide.md patterns; selected via ``use_pallas``/KTPU_PALLAS)."""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _row_lse(logk: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    return jax.scipy.special.logsumexp(logk + v[None, :], axis=1)


def _col_lse(logk: jnp.ndarray, u: jnp.ndarray) -> jnp.ndarray:
    return jax.scipy.special.logsumexp(logk + u[:, None], axis=0)


def _scale_jnp(logk, log_r, log_c, iters):
    """Alternating log-domain scaling; columns clipped at 0 (inequality)."""

    def body(carry, _):
        u, v = carry
        u = log_r - _row_lse(logk, v)
        u = jnp.where(jnp.isfinite(u), u, NEG_INF)
        v = jnp.minimum(log_c - _col_lse(logk, u), 0.0)
        v = jnp.where(jnp.isfinite(v), v, 0.0)
        return (u, v), None

    P, N = logk.shape
    (u, v), _ = jax.lax.scan(
        body, (jnp.zeros((P,)), jnp.zeros((N,))), None, length=iters
    )
    return u, v


# ---------------------------------------------------------------------------
# Pallas TPU kernels: tiled row/column logsumexp scaling passes
# ---------------------------------------------------------------------------


def _u_kernel(logk_ref, v_ref, logr_ref, u_ref):
    """One row-scaling pass over a (Bp, N) tile: u = log_r - lse(logk+v)."""
    x = logk_ref[:] + v_ref[:]  # (Bp, N)
    m = jnp.max(x, axis=1, keepdims=True)
    m = jnp.maximum(m, NEG_INF)  # all-masked rows stay finite
    lse = jnp.log(jnp.sum(jnp.exp(x - m), axis=1, keepdims=True) + 1e-30) + m
    u = logr_ref[:] - lse[:, 0]
    u_ref[:] = jnp.where(u > NEG_INF / 2, u, NEG_INF)


def _v_kernel(logk_ref, u_ref, logc_ref, v_ref):
    """One column-scaling pass over a (P, Bn) tile, clipped at 0."""
    x = logk_ref[:] + u_ref[:][:, None]  # (P, Bn)
    m = jnp.max(x, axis=0, keepdims=True)
    m = jnp.maximum(m, NEG_INF)
    lse = jnp.log(jnp.sum(jnp.exp(x - m), axis=0, keepdims=True) + 1e-30) + m
    v = jnp.minimum(logc_ref[:] - lse[0, :], 0.0)
    v_ref[:] = jnp.where(v > NEG_INF / 2, v, 0.0)


def _scale_pallas(logk, log_r, log_c, iters, block_p=256, block_n=512,
                  interpret=False):
    from jax.experimental import pallas as pl

    P0, N0 = logk.shape
    bp, bn = min(block_p, P0), min(block_n, N0)
    # pad to block multiples (grid uses exact division); padded rows ship
    # nothing (log_r = -inf) and padded columns accept nothing (their
    # kernel column is -inf so their v never matters)
    P = ((P0 + bp - 1) // bp) * bp
    N = ((N0 + bn - 1) // bn) * bn
    if (P, N) != (P0, N0):
        logk = jnp.pad(logk, ((0, P - P0), (0, N - N0)),
                       constant_values=NEG_INF)
        log_r = jnp.pad(log_r, (0, P - P0), constant_values=NEG_INF)
        log_c = jnp.pad(log_c, (0, N - N0), constant_values=NEG_INF)
    u_call = pl.pallas_call(
        _u_kernel,
        grid=(P // bp,),
        in_specs=[
            pl.BlockSpec((bp, N), lambda i: (i, 0)),
            pl.BlockSpec((N,), lambda i: (0,)),
            pl.BlockSpec((bp,), lambda i: (i,)),
        ],
        out_specs=pl.BlockSpec((bp,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((P,), logk.dtype),
        interpret=interpret,
    )
    v_call = pl.pallas_call(
        _v_kernel,
        grid=(N // bn,),
        in_specs=[
            pl.BlockSpec((P, bn), lambda j: (0, j)),
            pl.BlockSpec((P,), lambda j: (0,)),
            pl.BlockSpec((bn,), lambda j: (j,)),
        ],
        out_specs=pl.BlockSpec((bn,), lambda j: (j,)),
        out_shape=jax.ShapeDtypeStruct((N,), logk.dtype),
        interpret=interpret,
    )

    def body(carry, _):
        u, v = carry
        u = u_call(logk, v, log_r)
        v = v_call(logk, u, log_c)
        return (u, v), None

    (u, v), _ = jax.lax.scan(
        body, (jnp.zeros((P,), logk.dtype), jnp.zeros((N,), logk.dtype)),
        None, length=iters,
    )
    return u[:P0], v[:N0]


def use_pallas() -> bool:
    """Pallas path policy: on by default on real TPU, opt-in elsewhere
    (KTPU_PALLAS=1 forces interpret-mode execution for testing)."""
    env = os.environ.get("KTPU_PALLAS", "")
    if env == "0":
        return False
    if env == "1":
        return True
    return jax.default_backend() == "tpu"


def sinkhorn_plan(
    score: jnp.ndarray,
    mask: jnp.ndarray,
    capacity: jnp.ndarray,
    eps: float = 0.5,
    iters: int = 25,
    pallas: Optional[bool] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Transport plan (P, N): plan[p, j] ≈ how much of pod p's unit demand
    node j serves at equilibrium. Row sums <= 1 (== 1 when the pod fits
    anywhere with spare capacity); column sums <= capacity + O(tolerance).
    """
    score = score.astype(jnp.float32)
    row_ok = jnp.any(mask, axis=1)
    logk = jnp.where(mask, score / eps, NEG_INF)
    log_r = jnp.where(row_ok, 0.0, NEG_INF)  # demand 1 per schedulable pod
    log_c = jnp.where(capacity > 0, jnp.log(jnp.maximum(capacity, 1e-30)), NEG_INF)
    if pallas is None:
        pallas = use_pallas()
    if pallas:
        interp = (jax.default_backend() != "tpu") if interpret is None else interpret
        u, v = _scale_pallas(logk, log_r, log_c, iters, interpret=interp)
    else:
        u, v = _scale_jnp(logk, log_r, log_c, iters)
    plan = jnp.exp(
        jnp.clip(logk + u[:, None] + v[None, :], NEG_INF, 30.0)
    )
    return jnp.where(mask, plan, 0.0)
