"""Scenario-pack cost kernels and placement-quality reductions — the
device half of ``kubernetes_tpu/scenarios`` (the pluggable-objective
subsystem; see docs/scenarios.md).

Two cost kernels fold scenario objectives into the ``extra_score``
term every solver tier already consumes (batch rounds, the Sinkhorn
transport plan, the greedy oracle, the exact Hungarian — the objective
rides the whole degradation ladder unchanged):

- :func:`consolidation_bias` — the "Priority Matters"-style packing
  term: a flat bonus on nodes that already host pods, so the argmax /
  transport plan prefers filling started nodes over opening empty ones
  (the usage-DEPENDENT half of the consolidation objective is the stock
  ``MostRequestedPriority`` kernel, re-weighted by the pack — it is
  recomputed per round; this bias covers the open-a-new-node step
  function those per-round fractions cannot see).
- :func:`gang_topology_score` — the Tesserae-style DL-gang term: each
  gang is assigned a *home slice* host-side (scenarios/packs.py greedy,
  biggest gang -> freest slice) and every member scores nodes by slice
  distance to home. Distance is the hierarchical ICI metric of
  :func:`slice_distance` over the packer's zone index: zone == TPU
  slice, ``superpod`` consecutive slices share a superpod (one ICI
  hop), anything further is fabric (two hops).

One quality reduction, :func:`quality_reduce`, turns the cycle's final
device usage + assignment into a tiny fixed-layout f32 vector
(:data:`QUALITY_FIELDS`) — nodes used, headroom, fragmentation,
priority-weighted headroom — that crosses the boundary as one ~28 B
readback at the cycle's existing host sync (the PR-7 budget holds; the
raw (P, N)/(N, R) planes never cross). Everything here is pure jnp —
tracer-safe, no host syncs (graftlint R2/R3/R7 clean, pinned by
``testing.lint_clean`` in tests/test_scenarios.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from kubernetes_tpu.snapshot import RES_CPU, RES_MEM, RES_PODS

#: host-decode layout of the :func:`quality_reduce` vector (one f32
#: slot per name, in order). scenarios/quality.py owns the decode.
QUALITY_FIELDS = (
    "nodes_used",            # valid nodes hosting >= 1 pod after the cycle
    "nodes_used_batch",      # valid nodes that RECEIVED >= 1 pod this cycle
    "placed",                # pods this assignment placed (cross-check)
    "headroom",              # mean over valid nodes of min(cpu, mem) free frac
    "fragmentation",         # fraction of free CPU stranded on nodes too
    #                          empty-handed for the batch's mean request
    "priority_headroom",     # placed-pod mean of node free frac, weighted
    #                          by (priority - min_priority + 1)
    "free_cpu_frac",         # cluster-wide free CPU fraction
)


@partial(jax.jit, static_argnames=("superpod",))
def slice_distance(za: jnp.ndarray, zb: jnp.ndarray,
                   superpod: int = 4) -> jnp.ndarray:
    """Hierarchical ICI distance between two slice (zone) indices:
    0 = same slice, 1 = same superpod (``superpod`` consecutive slice
    indices per group), 2 = cross-fabric. Unlabeled (-1) indices are
    always cross-fabric. Broadcasts like the operands."""
    sp = jnp.maximum(jnp.int32(superpod), 1)
    labeled = (za >= 0) & (zb >= 0)
    same = labeled & (za == zb)
    near = labeled & ((za // sp) == (zb // sp))
    return jnp.where(same, 0, jnp.where(near, 1, 2)).astype(jnp.int32)


@partial(jax.jit, static_argnames=("fill_block",))
def consolidation_bias(pod_valid: jnp.ndarray, nodes,
                       weight: jnp.ndarray,
                       fill_block: int = 64) -> jnp.ndarray:
    """(P, N) packing bias, two terms:

    - ``weight`` points on every valid node that already hosts a pod
      (snapshot-start occupancy — the in-cycle growth is the
      re-weighted MostRequested kernel's job);
    - a sub-integer **blocked fill-order** term: nodes prefer in blocks
      of ``fill_block`` consecutive rows (block k biased ``-0.5*k/B``).
      The stock kernels are integer-valued, so the term breaks only
      EXACT ties — and that is the whole point: an all-empty cluster
      ties everywhere, the round solver's rotation tie-break would fan
      the batch evenly across all N nodes (the spreading it exists
      for), and nodes-used would never drop. Blocking the order keeps
      per-round parallelism (ties persist WITHIN a block, so a round
      still admits ~fill_block * per_node_cap pods) while the batch
      concentrates into a demand-sized prefix of blocks.

    ``weight`` rides as a device scalar so one compiled program serves
    every configured cost weight; ``fill_block`` is a static key."""
    occupied = nodes.valid & (nodes.requested[:, RES_PODS] > 0)
    N = nodes.valid.shape[0]
    nblocks = max((N + fill_block - 1) // fill_block, 1)
    blk = (jnp.arange(N, dtype=jnp.int32) // max(fill_block, 1))
    order = -0.5 * blk.astype(jnp.float32) / nblocks
    row = (jnp.where(occupied, weight, 0.0) + order).astype(jnp.float32)
    return jnp.broadcast_to(
        row[None, :], (pod_valid.shape[0], N)
    ) * pod_valid[:, None]


@partial(jax.jit, static_argnames=("superpod",))
def gang_topology_score(home_zone: jnp.ndarray, nodes,
                        weight: jnp.ndarray,
                        superpod: int = 4) -> jnp.ndarray:
    """(P, N) slice-locality score for gang members: ``weight`` points
    per ICI hop SAVED relative to cross-fabric (so home-slice nodes
    score ``2*weight``, same-superpod ``weight``, fabric 0). Pods
    without a gang home (``home_zone < 0``) contribute an all-zero row
    — the term is invisible to gangless traffic."""
    d = slice_distance(home_zone[:, None], nodes.zone_id[None, :],
                       superpod=superpod)  # (P, N)
    score = weight * (2 - d).astype(jnp.float32)
    gated = jnp.where((home_zone >= 0)[:, None], score, 0.0)
    return gated * nodes.valid[None, :]


@jax.jit
def quality_reduce(assigned: jnp.ndarray, usage_requested: jnp.ndarray,
                   pods, nodes) -> jnp.ndarray:
    """The per-cycle placement-quality vector (layout
    :data:`QUALITY_FIELDS`): one jitted reduction over the FINAL device
    usage and assignment — gang rollbacks already applied by the caller
    — whose (7,)-f32 result rides the cycle's existing readback
    boundary. ``assigned`` is the (P,) int32 row vector (node row or
    -1); ``usage_requested`` the final (N, R) requested matrix."""
    valid_n = nodes.valid
    alloc = nodes.allocatable
    placed_mask = pods.valid & (assigned >= 0)
    ac = jnp.clip(assigned, 0, valid_n.shape[0] - 1)

    pod_cnt = usage_requested[:, RES_PODS]
    nodes_used = jnp.sum(valid_n & (pod_cnt > 0), dtype=jnp.int32)
    got_batch = jnp.zeros((valid_n.shape[0],), jnp.int32).at[
        jnp.where(placed_mask, ac, 0)].add(placed_mask.astype(jnp.int32))
    nodes_used_batch = jnp.sum((got_batch > 0) & valid_n, dtype=jnp.int32)
    placed = jnp.sum(placed_mask, dtype=jnp.int32)

    cap_cpu = jnp.maximum(alloc[:, RES_CPU], 1e-9)
    cap_mem = jnp.maximum(alloc[:, RES_MEM], 1e-9)
    free_cpu = jnp.maximum(alloc[:, RES_CPU] - usage_requested[:, RES_CPU],
                           0.0)
    free_mem = jnp.maximum(alloc[:, RES_MEM] - usage_requested[:, RES_MEM],
                           0.0)
    min_free_frac = jnp.minimum(free_cpu / cap_cpu, free_mem / cap_mem)
    n_valid = jnp.maximum(jnp.sum(valid_n, dtype=jnp.int32), 1)
    headroom = jnp.sum(jnp.where(valid_n, min_free_frac, 0.0)) / n_valid

    # fragmentation: share of total free CPU sitting on nodes whose free
    # CPU cannot fit even the batch's MEAN request — capacity the
    # residual workload cannot actually use. Consolidation drives it
    # down (free capacity concentrates on whole empty nodes).
    mean_req = jnp.sum(
        jnp.where(pods.valid[:, None], pods.req, 0.0)[:, RES_CPU]
    ) / jnp.maximum(jnp.sum(pods.valid, dtype=jnp.int32), 1)
    total_free = jnp.sum(jnp.where(valid_n, free_cpu, 0.0))
    stranded = jnp.sum(
        jnp.where(valid_n & (free_cpu < jnp.maximum(mean_req, 1e-9)),
                  free_cpu, 0.0))
    fragmentation = stranded / jnp.maximum(total_free, 1e-9)

    # priority-weighted headroom: placed pods' node free fraction,
    # weighted toward the high tiers — how much room the pods that
    # matter most landed next to.
    pri = pods.priority.astype(jnp.float32)
    pri_min = jnp.min(jnp.where(placed_mask, pri, jnp.inf))
    w = jnp.where(placed_mask,
                  pri - jnp.where(jnp.isfinite(pri_min), pri_min, 0.0) + 1.0,
                  0.0)
    pod_free = min_free_frac[ac]
    pri_headroom = jnp.sum(w * pod_free) / jnp.maximum(jnp.sum(w), 1e-9)

    total_cap = jnp.sum(jnp.where(valid_n, alloc[:, RES_CPU], 0.0))
    free_cpu_frac = total_free / jnp.maximum(total_cap, 1e-9)

    return jnp.stack([
        nodes_used.astype(jnp.float32),
        nodes_used_batch.astype(jnp.float32),
        placed.astype(jnp.float32),
        headroom.astype(jnp.float32),
        fragmentation.astype(jnp.float32),
        pri_headroom.astype(jnp.float32),
        free_cpu_frac.astype(jnp.float32),
    ])
