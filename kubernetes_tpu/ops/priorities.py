"""Vectorized Score priorities — the reference's Map/Reduce priority library
(``pkg/scheduler/algorithm/priorities/``) recast as fused (pods x nodes) f32
kernels.

The reference maps each priority per node under a 16-goroutine fan-out
(``generic_scheduler.go:738``), reduces (normalizes) per pod, then takes the
weighted sum (``:799-829``). Here each priority emits the whole (P, N) matrix
at once; reduces are per-row ops; the weighted sum is one fused combine.

Go's integer arithmetic (scores are int64 0..10 with repeated integer
division) is emulated with ``floor(x + eps)`` in f32 — exact on realistic
resource values; see ``_idiv``.
"""

from __future__ import annotations

from typing import Callable, Dict, NamedTuple

import jax
import jax.numpy as jnp

from kubernetes_tpu.api.types import MAX_PRIORITY
from kubernetes_tpu.ops.arrays import DeviceNodes, DevicePods, DeviceSelectors
from kubernetes_tpu.ops.predicates import preferred_program_score

_EPS = 1e-5


def _idiv(num: jnp.ndarray, den: jnp.ndarray) -> jnp.ndarray:
    """Go-style integer division num/den in f32: floor with a small epsilon
    to absorb f32 rounding below exact integer ratios."""
    return jnp.floor(num / jnp.maximum(den, 1e-30) + _EPS)


def _normalize_reduce(raw: jnp.ndarray, mask: jnp.ndarray, reverse: bool) -> jnp.ndarray:
    """priorities/reduce.go NormalizeReduce: per pod, scale scores so the max
    becomes MaxPriority; if max==0 -> all MaxPriority when reversed, else 0.

    ``mask`` is the pod's Filter feasibility row — the reference reduces over
    the *filtered* node list only (PrioritizeNodes receives filteredNodes,
    generic_scheduler.go:684), so the max is taken over feasible nodes."""
    masked = jnp.where(mask, raw, 0.0)
    mx = jnp.max(masked, axis=1, keepdims=True)  # (P, 1)
    scaled = _idiv(MAX_PRIORITY * raw, jnp.where(mx > 0, mx, 1.0))
    scaled = jnp.where(mx > 0, scaled, 0.0)
    if reverse:
        scaled = jnp.where(mx > 0, MAX_PRIORITY - scaled, float(MAX_PRIORITY))
    return scaled


def _requested_fractions(pods: DevicePods, nodes: DeviceNodes):
    """(cpu, mem) total nonzero-request fractions after placing each pod on
    each node — shared scaffold of the ResourceAllocationPriority family
    (resource_allocation.go:39)."""
    cpu_req = pods.nonzero_req[:, 0:1] + nodes.nonzero_req[None, :, 0]
    mem_req = pods.nonzero_req[:, 1:2] + nodes.nonzero_req[None, :, 1]
    cpu_cap = nodes.allocatable[None, :, 0]
    mem_cap = nodes.allocatable[None, :, 1]
    return cpu_req, mem_req, cpu_cap, mem_cap


def least_requested(pods, nodes, sel, topo, mask) -> jnp.ndarray:
    """least_requested.go: ((cap-req)*10/cap + (cap-req)*10/cap)/2, integer
    divisions preserved; req>cap or cap==0 scores 0."""
    cpu_req, mem_req, cpu_cap, mem_cap = _requested_fractions(pods, nodes)

    def score(req, cap):
        s = _idiv((cap - req) * MAX_PRIORITY, cap)
        return jnp.where((cap <= 0) | (req > cap), 0.0, s)

    return _idiv(score(cpu_req, cpu_cap) + score(mem_req, mem_cap), 2.0)


def most_requested(pods, nodes, sel, topo, mask) -> jnp.ndarray:
    """most_requested.go: (req*10/cap) averaged — the bin-packing dual."""
    cpu_req, mem_req, cpu_cap, mem_cap = _requested_fractions(pods, nodes)

    def score(req, cap):
        s = _idiv(req * MAX_PRIORITY, cap)
        return jnp.where((cap <= 0) | (req > cap), 0.0, s)

    return _idiv(score(cpu_req, cpu_cap) + score(mem_req, mem_cap), 2.0)


def balanced_allocation(pods, nodes, sel, topo, mask) -> jnp.ndarray:
    """balanced_resource_allocation.go (two-resource form): score =
    int((1 - |cpuFrac - memFrac|) * 10); any fraction >= 1 scores 0."""
    cpu_req, mem_req, cpu_cap, mem_cap = _requested_fractions(pods, nodes)
    cf = jnp.where(cpu_cap > 0, cpu_req / jnp.maximum(cpu_cap, 1e-30), 1.0)
    mf = jnp.where(mem_cap > 0, mem_req / jnp.maximum(mem_cap, 1e-30), 1.0)
    diff = jnp.abs(cf - mf)
    score = jnp.floor((1.0 - diff) * MAX_PRIORITY + _EPS)
    return jnp.where((cf >= 1.0) | (mf >= 1.0), 0.0, score)


def _node_affinity_raw(pods, nodes, sel) -> jnp.ndarray:
    """Usage-invariant raw weight sums (the map phase) — hoistable out of
    the round loop; only the mask-dependent NormalizeReduce is per-round."""
    prog = preferred_program_score(sel, nodes)  # (Gp, N)
    idx = jnp.clip(pods.prefprog_id, 0, prog.shape[0] - 1)
    return jnp.where((pods.prefprog_id >= 0)[:, None], prog[idx], 0.0)


def node_affinity(pods, nodes, sel, topo, mask) -> jnp.ndarray:
    """node_affinity.go: weight-sum of matched PreferredDuringScheduling
    terms, NormalizeReduce(10, false)."""
    return _normalize_reduce(_node_affinity_raw(pods, nodes, sel), mask,
                             reverse=False)


def _taint_toleration_raw(pods, nodes, sel) -> jnp.ndarray:
    """Usage-invariant intolerable-taint counts (taints never change
    within a batch) — the matmul half of the kernel, hoistable."""
    tol_idx = jnp.clip(pods.tolset_id, 0, sel.tol_soft_mh.shape[0] - 1)
    tol_rows = jnp.where((pods.tolset_id >= 0)[:, None], sel.tol_soft_mh[tol_idx], 0.0)
    soft_count = jnp.sum(nodes.taint_soft_mh, axis=1)  # (N,)
    tolerated = tol_rows @ nodes.taint_soft_mh.T  # (P, N)
    return soft_count[None, :] - tolerated


def taint_toleration(pods, nodes, sel, topo, mask) -> jnp.ndarray:
    """taint_toleration.go: count PreferNoSchedule taints not tolerated,
    NormalizeReduce(10, reverse=true)."""
    return _normalize_reduce(_taint_toleration_raw(pods, nodes, sel), mask,
                             reverse=True)


def image_locality(pods, nodes, sel, topo, mask) -> jnp.ndarray:
    """image_locality.go: sum of (size * nodes-with-image/total-nodes) over
    the pod's images present on the node, clamped to [23MB, 1000MB] and
    scaled to 0..10."""
    mb = 1024.0 * 1024.0
    lo, hi = 23.0 * mb, 1000.0 * mb
    total = jnp.maximum(jnp.sum(nodes.valid.astype(jnp.float32)), 1.0)
    num_nodes = jnp.sum(
        jnp.where(nodes.valid[:, None], nodes.image_mh, 0.0), axis=0
    )  # (Ui,) nodes having each image
    spread = num_nodes / total
    # truncation to int64 per image (scaledImageScore) then summed
    scaled = jnp.floor(sel.image_sizes * spread + _EPS)  # (Ui,)
    sum_scores = pods.image_mh @ (nodes.image_mh * scaled[None, :]).T  # (P, N)
    clamped = jnp.clip(sum_scores, lo, hi)
    return _idiv(MAX_PRIORITY * (clamped - lo), hi - lo)


def selector_spread(pods, nodes, sel, topo, mask) -> jnp.ndarray:
    """selector_spreading.go: map = count of same-namespace pods on the node
    matching all owner selectors; reduce = 10*(max-count)/max blended 1/3
    with the zone-level equivalent at 2/3 (zoneWeighting, :34) when zones
    exist."""
    idx = jnp.clip(pods.owner_id, 0, nodes.owner_counts.shape[1] - 1)
    counts = jnp.where(
        (pods.owner_id >= 0)[:, None], nodes.owner_counts.T[idx], 0.0
    )  # (P, N)
    counts = jnp.where(mask, counts, 0.0)
    max_node = jnp.max(counts, axis=1, keepdims=True)  # (P, 1)

    # zone aggregation as a one-hot matmul: Zmat (N, Z)
    n_zones = nodes.zone_valid.shape[0]
    has_zone = nodes.zone_id >= 0
    zid = jnp.clip(nodes.zone_id, 0, n_zones - 1)
    zmat = (
        (zid[:, None] == jnp.arange(n_zones)[None, :])
        & has_zone[:, None]
    ).astype(jnp.float32)  # (N, Z)
    zcounts = counts @ zmat  # (P, Z) — per-pod per-zone matched-pod totals
    # zones present *for this pod* = zones containing a feasible node
    # (the reference builds countsByZone from the pod's scored node list)
    zone_present = (mask.astype(jnp.float32) @ zmat) > 0  # (P, Z)
    max_zone = jnp.max(jnp.where(zone_present, zcounts, -jnp.inf), axis=1, keepdims=True)
    have_zones = jnp.any(zone_present, axis=1, keepdims=True)  # (P, 1)

    node_score = jnp.where(
        max_node > 0,
        MAX_PRIORITY * (max_node - counts) / jnp.maximum(max_node, 1e-30),
        float(MAX_PRIORITY),
    )
    zcount_of_node = jnp.take_along_axis(
        zcounts, jnp.broadcast_to(zid[None, :], (zcounts.shape[0], zid.shape[0])), axis=1
    )  # (P, N)
    zone_score = jnp.where(
        max_zone > 0,
        MAX_PRIORITY * (max_zone - zcount_of_node) / jnp.maximum(max_zone, 1e-30),
        float(MAX_PRIORITY),
    )
    blend = jnp.where(
        have_zones & has_zone[None, :],
        node_score * (1.0 / 3.0) + zone_score * (2.0 / 3.0),
        node_score,
    )
    return jnp.floor(blend + _EPS)  # reference truncates the final float


def node_prefer_avoid(pods, nodes, sel, topo, mask) -> jnp.ndarray:
    """node_prefer_avoid_pods.go: 0 when the node's preferAvoidPods
    annotation lists the pod's controller owner, else 10 (weight 10000 in
    the default provider drowns other priorities)."""
    idx = jnp.clip(pods.owner_uid_id, 0, nodes.avoid_mh.shape[1] - 1)
    avoided = jnp.where(
        (pods.owner_uid_id >= 0)[:, None], nodes.avoid_mh.T[idx], 0.0
    )
    return jnp.where(avoided > 0, 0.0, float(MAX_PRIORITY))


def equal_priority(pods, nodes, sel, topo, mask) -> jnp.ndarray:
    """generic_scheduler.go:840 EqualPriority."""
    return jnp.ones((pods.req.shape[0], nodes.allocatable.shape[0]), jnp.float32)


def inter_pod_affinity(pods, nodes, sel, topo, mask) -> jnp.ndarray:
    """interpod_affinity.go CalculateInterPodAffinityPriority (symmetric
    weighted term counts, min/max-normalized). No-op (all zeros) when no
    topology tables were packed."""
    if topo is None:
        return jnp.zeros((pods.req.shape[0], nodes.allocatable.shape[0]), jnp.float32)
    from kubernetes_tpu.ops.topology import inter_pod_affinity_score

    return inter_pod_affinity_score(pods, nodes, topo, mask)


def even_pods_spread(pods, nodes, sel, topo, mask) -> jnp.ndarray:
    """even_pods_spread.go CalculateEvenPodsSpreadPriority (feature-gated in
    the reference; enabled here whenever soft constraints exist)."""
    if topo is None:
        return jnp.zeros((pods.req.shape[0], nodes.allocatable.shape[0]), jnp.float32)
    from kubernetes_tpu.ops.predicates import selector_program_match
    from kubernetes_tpu.ops.topology import even_pods_spread_score

    prog = selector_program_match(sel, nodes)
    return even_pods_spread_score(pods, nodes, topo, prog, mask)


#: RequestedToCapacityRatio default shape: least-utilized preferred
#: (requested_to_capacity_ratio.go:41 defaultFunctionShape).
DEFAULT_FUNCTION_SHAPE = ((0, 10), (100, 0))


def _broken_linear(p: jnp.ndarray, shape) -> jnp.ndarray:
    """buildBrokenLinearFunction (requested_to_capacity_ratio.go:110):
    piecewise-linear through integer (utilization, score) points with Go
    int64 division (truncation toward zero — jnp.fix). ``shape`` is a
    static tuple so each segment unrolls into the trace."""
    xs = [float(x) for x, _ in shape]
    ys = [float(y) for _, y in shape]
    out = jnp.full_like(p, ys[-1])
    for i in reversed(range(len(xs))):
        if i == 0:
            seg = jnp.full_like(p, ys[0])
        else:
            seg = ys[i - 1] + jnp.trunc(
                (ys[i] - ys[i - 1]) * (p - xs[i - 1]) / (xs[i] - xs[i - 1])
            )
        out = jnp.where(p <= xs[i], seg, out)
    return out


def make_requested_to_capacity_ratio(shape=DEFAULT_FUNCTION_SHAPE) -> "PriorityFn":
    """RequestedToCapacityRatioResourceAllocationPriority
    (requested_to_capacity_ratio.go:87): per-resource utilization percent
    through the shape function, cpu/mem averaged with integer division.

    Utilization uses the scaffold's requested = pod nonzero request + node
    nonzero usage (resource_allocation.go:49-58). The percent floor adds a
    1e-4 epsilon before flooring: Go computes (cap-req)*100/cap in exact
    int64 while we ride f32 — the epsilon absorbs representation error so
    exact-integer percentages (the common round-number case) floor the Go
    way; adversarial near-boundary byte counts may differ by 1 score step.
    """

    def one(req, cap):
        bad = (cap <= 0) | (req > cap)
        pct = 100.0 - jnp.floor(
            (cap - req) * 100.0 / jnp.maximum(cap, 1.0) + 1e-4
        )
        return _broken_linear(jnp.where(bad, 100.0, pct), shape)

    def kernel(pods, nodes, sel, topo, mask) -> jnp.ndarray:
        cpu_req, mem_req, cpu_cap, mem_cap = _requested_fractions(pods, nodes)
        cpu = one(cpu_req, jnp.broadcast_to(cpu_cap, cpu_req.shape))
        mem = one(mem_req, jnp.broadcast_to(mem_cap, mem_req.shape))
        return jnp.trunc((cpu + mem) / 2.0)

    return kernel


def make_node_label(key_id: int, presence: bool) -> "PriorityFn":
    """NodeLabelPriority (node_label.go:47): MaxPriority when the node's
    having label ``key_id`` agrees with ``presence``, else 0. ``key_id``
    indexes the label-key universe (intern the label before packing)."""

    def kernel(pods, nodes, sel, topo, mask) -> jnp.ndarray:
        has = nodes.key_mh[:, key_id] > 0  # (N,)
        hit = has if presence else ~has
        row = jnp.where(hit, float(MAX_PRIORITY), 0.0)
        return jnp.broadcast_to(row[None, :], (pods.req.shape[0], nodes.n))

    return kernel


def resource_limits(pods, nodes, sel, topo, mask) -> jnp.ndarray:
    """ResourceLimitsPriority (resource_limits.go:44): score 1 when the
    node's allocatable satisfies the pod's cpu OR memory limit (a declared,
    non-zero limit that fits), else 0."""
    cap = nodes.allocatable  # (N, R); cols 0/1 = cpu_milli/memory (RES_CPU/RES_MEM)
    cpu_ok = (pods.limits[:, 0:1] > 0) & (pods.limits[:, 0:1] <= cap[:, 0][None, :])
    mem_ok = (pods.limits[:, 1:2] > 0) & (pods.limits[:, 1:2] <= cap[:, 1][None, :])
    return (cpu_ok | mem_ok).astype(jnp.float32)


PriorityFn = Callable[..., jnp.ndarray]  # (pods, nodes, sel, topo, mask) -> (P, N)

#: Registry name -> kernel; names mirror factory registrations
#: (algorithmprovider/defaults/register_priorities.go).
PRIORITY_REGISTRY: Dict[str, PriorityFn] = {
    "LeastRequestedPriority": least_requested,
    "MostRequestedPriority": most_requested,
    "BalancedResourceAllocation": balanced_allocation,
    "NodeAffinityPriority": node_affinity,
    "TaintTolerationPriority": taint_toleration,
    "ImageLocalityPriority": image_locality,
    "SelectorSpreadPriority": selector_spread,
    "NodePreferAvoidPodsPriority": node_prefer_avoid,
    "EqualPriority": equal_priority,
    "InterPodAffinityPriority": inter_pod_affinity,
    "EvenPodsSpreadPriority": even_pods_spread,
    "RequestedToCapacityRatioPriority": make_requested_to_capacity_ratio(),
    "ResourceLimitsPriority": resource_limits,
}


def register_priority(name: str, fn: PriorityFn) -> None:
    """Add a custom-configured priority (the factory/plugins.go
    RegisterPriorityMapReduceFunction analog) — e.g. a NodeLabelPriority
    bound to a specific label, or a RequestedToCapacityRatio with a custom
    shape. Weights dicts may then reference ``name``."""
    PRIORITY_REGISTRY[name] = fn

#: Default provider weights (defaults.go:119 defaultPriorities).
#: EvenPodsSpreadPriority joins via the EvenPodsSpread feature gate
#: (defaults.go:91-100), not the default set.
DEFAULT_WEIGHTS: Dict[str, float] = {
    "SelectorSpreadPriority": 1,
    "InterPodAffinityPriority": 1,
    "LeastRequestedPriority": 1,
    "BalancedResourceAllocation": 1,
    "NodePreferAvoidPodsPriority": 10000,
    "NodeAffinityPriority": 1,
    "TaintTolerationPriority": 1,
    "ImageLocalityPriority": 1,
}


#: the exact full-matrix constant each kernel produces when its inputs are
#: absent from the snapshot (verified by tests/test_priorities.py gating
#: equality): reverse-normalized kernels and spread/avoid fill MaxPriority
#: everywhere (NormalizeReduce's max==0 branch is mask-independent),
#: forward-normalized and sum-based kernels fill 0.
EMPTY_CONSTANTS: Dict[str, float] = {
    "NodeAffinityPriority": 0.0,
    "TaintTolerationPriority": float(MAX_PRIORITY),
    "ImageLocalityPriority": 0.0,
    "SelectorSpreadPriority": float(MAX_PRIORITY),
    "NodePreferAvoidPodsPriority": float(MAX_PRIORITY),
    "ResourceLimitsPriority": 0.0,
    # the two topology scores normalize all-zero raw forward -> 0
    "InterPodAffinityPriority": 0.0,
    "EvenPodsSpreadPriority": 0.0,
}

#: the stock kernels the constants were derived from: register_priority()
#: may rebind a registry name, and the gate must never constant-fold a
#: custom kernel (its empty-input behavior is unknown)
_STOCK_KERNELS: Dict[str, PriorityFn] = {
    name: PRIORITY_REGISTRY[name] for name in EMPTY_CONSTANTS
}


def empty_priorities(node_table, pod_table) -> tuple:
    """Host-side feature gate (the device twin of the reference skipping
    plugins a profile doesn't enable): names whose kernels provably
    produce their :data:`EMPTY_CONSTANTS` for THIS snapshot because the
    inputs they read are entirely absent. Computed on the packed host
    tables (numpy, no device sync) and threaded into the solvers as a
    STATIC jit key — the round loop then adds a scalar instead of paying
    the kernel's matmul + masked reductions every round
    (benchres/solver_profile_cpu.json: these were 2/3 of scoring cost on
    constraint-light workloads)."""
    import numpy as np

    out = []
    if pod_table.prefprog_id.size == 0 or (pod_table.prefprog_id < 0).all():
        out.append("NodeAffinityPriority")  # no preferred node affinity
    if node_table.taint_soft_mh.size == 0 or node_table.taint_soft_mh.sum() == 0:
        out.append("TaintTolerationPriority")  # no PreferNoSchedule taints
    if pod_table.image_mh.size == 0 or pod_table.image_mh.sum() == 0:
        out.append("ImageLocalityPriority")  # no pod lists images
    if pod_table.owner_id.size == 0 or (pod_table.owner_id < 0).all():
        out.append("SelectorSpreadPriority")  # no spread-owner selectors
    if (node_table.avoid_mh.size == 0 or node_table.avoid_mh.sum() == 0
            or (pod_table.owner_uid_id < 0).all()):
        out.append("NodePreferAvoidPodsPriority")
    if pod_table.limits is None or np.asarray(pod_table.limits).max(initial=0) <= 0:  # graftlint: disable=R7 -- host pack table, no device sync
        out.append("ResourceLimitsPriority")
    # topology scores: gate only with full evidence — no (anti)affinity on
    # any batch pod AND zero node-side anti/sym term counts (symmetry
    # inputs from existing pods); spread presence is a packed pod column
    if (not pod_table.has_aff.any()
            and node_table.anti_counts.sum() == 0
            and node_table.sym_counts.sum() == 0):
        out.append("InterPodAffinityPriority")
    if ((pod_table.spread_hard_id < 0).all()
            and (pod_table.spread_soft_id < 0).all()):
        out.append("EvenPodsSpreadPriority")
    return tuple(out)


def solver_gates(node_table, pod_table):
    """The one evidence rule every solver caller needs, in one place:
    ``(skip_priorities, no_ports, no_pod_affinity, no_spread)`` for this
    snapshot+batch. The two topology MASK gates share the score gates'
    evidence by construction."""
    from kubernetes_tpu.ops.predicates import pods_have_no_ports

    skip = empty_priorities(node_table, pod_table)
    return (skip, pods_have_no_ports(pod_table),
            "InterPodAffinityPriority" in skip,
            "EvenPodsSpreadPriority" in skip)


#: a snapshot of the whole stock registry at import time: the fused
#: normalize path (and its integer-sum exactness argument) only applies
#: when every ACTIVE kernel is stock — register_priority() rebinding any
#: name disables fusion for configs that use it
_ALL_STOCK_KERNELS: Dict[str, PriorityFn] = dict(PRIORITY_REGISTRY)


def _fusable(weights: Dict[str, float], skip) -> bool:
    """True when the NA+TT fused accumulate is provably bit-identical:
    every active kernel is stock (all stock kernels floor their scores to
    integer-valued f32 — verified across priorities.py and topology.py)
    and every weight is an integer, so all partial sums are exact f32
    integers (< 2^24) and addition regrouping cannot round."""
    for name, w in weights.items():
        if not w or name in skip:
            continue
        if PRIORITY_REGISTRY.get(name) is not _ALL_STOCK_KERNELS.get(name):
            return False
        if float(w) != int(w):
            return False
    return True


def _fused_pair_normalize(raw_fwd, raw_rev, mask, w_fwd, w_rev):
    """One-output fused form of the two hoisted-raw normalizes
    (NodeAffinity forward + TaintToleration reverse): on a
    Pallas-capable backend this routes to the two-pass HBM-minimal
    kernel pair (ops/fused_score.py); the jnp expression below is the
    universal fallback — identical per-element arithmetic to two
    :func:`_normalize_reduce` calls with the weighted pair landing as
    ONE (P, N) term. Exactness of the regrouped accumulation is the
    :func:`_fusable` integer argument; measured CPU effect of the jnp
    form is neutral-to-negative (XLA:CPU's own fusion already wins —
    benchres/fused_score_cpu.json), which is why the solver only engages
    fusion under the Pallas policy (see batch_assign)."""
    from kubernetes_tpu.ops.fused_score import fused_pair_normalize_device

    out = fused_pair_normalize_device(raw_fwd, raw_rev, mask, w_fwd, w_rev)
    if out is not None:
        return out
    masked_f = jnp.where(mask, raw_fwd, 0.0)
    mxf = jnp.max(masked_f, axis=1, keepdims=True)
    sf = _idiv(MAX_PRIORITY * raw_fwd, jnp.where(mxf > 0, mxf, 1.0))
    sf = jnp.where(mxf > 0, sf, 0.0)
    masked_r = jnp.where(mask, raw_rev, 0.0)
    mxr = jnp.max(masked_r, axis=1, keepdims=True)
    sr = _idiv(MAX_PRIORITY * raw_rev, jnp.where(mxr > 0, mxr, 1.0))
    sr = jnp.where(mxr > 0, sr, 0.0)
    sr = jnp.where(mxr > 0, MAX_PRIORITY - sr, float(MAX_PRIORITY))
    return w_fwd * sf + w_rev * sr


#: stock kernels whose full (P, N) score reads NO usage field and NO mask
#: — computable once per batch and reused every round verbatim
STATIC_FULL = ("ImageLocalityPriority", "NodePreferAvoidPodsPriority",
               "ResourceLimitsPriority")
#: stock kernels whose RAW map phase is usage-invariant but whose
#: NormalizeReduce depends on the per-round feasibility mask:
#: name -> (raw_fn, reverse)
STATIC_RAW = {
    "NodeAffinityPriority": (_node_affinity_raw, False),
    "TaintTolerationPriority": (_taint_toleration_raw, True),
}


def hoist_priorities(pods, nodes, sel,
                     weights: Dict[str, float] | None = None,
                     skip=()) -> Dict[str, tuple]:
    """The usage-invariant slice of scoring, computed ONCE per batch (the
    device analog of the reference computing plugin-independent state
    once per pod — and the round-4 answer to the profile finding that the
    static kernels were ~2/3 of per-round scoring cost,
    benchres/solver_profile_cpu.json). Returns ``{name: ("full", matrix)
    | ("raw", raw_matrix, reverse)}`` for :func:`run_priorities` to
    consume; skipped (gated) and custom-registered kernels are NOT
    hoisted — the gate constant-folds the former and the latter's
    static-ness is unknown."""
    weights = DEFAULT_WEIGHTS if weights is None else weights
    parts: Dict[str, tuple] = {}
    for name, w in weights.items():
        if not w or name in skip:
            continue
        if PRIORITY_REGISTRY.get(name) is not _STOCK_KERNELS.get(name):
            continue
        if name in STATIC_FULL:
            parts[name] = ("full",
                           PRIORITY_REGISTRY[name](pods, nodes, sel, None,
                                                   None))
        elif name in STATIC_RAW:
            raw_fn, reverse = STATIC_RAW[name]
            parts[name] = ("raw", raw_fn(pods, nodes, sel), reverse)
    return parts


def run_priorities(
    pods: DevicePods,
    nodes: DeviceNodes,
    sel: DeviceSelectors,
    mask: jnp.ndarray,
    weights: Dict[str, float] | None = None,
    topo=None,
    skip=(),
    hoisted: Dict[str, tuple] | None = None,
    fused: bool = False,
) -> jnp.ndarray:
    """PrioritizeNodes (generic_scheduler.go:684): weighted sum of all
    enabled priorities -> (P, N) f32 total score. ``skip`` names kernels
    (from :func:`empty_priorities`) replaced by their exact
    :data:`EMPTY_CONSTANTS` scalar. ``hoisted`` takes
    :func:`hoist_priorities` output; accumulation stays in weights-dict
    order with identical per-kernel arithmetic, so hoisted and unhoisted
    totals are bit-identical (pinned by tests/test_priorities.py).

    ``fused=True`` additionally collapses the two hoisted-raw normalizes
    (NodeAffinity + TaintToleration) into one single-output kernel —
    applied only when :func:`_fusable` proves the regrouped accumulation
    exact (all-stock kernels, integer weights), so it is ALWAYS
    bit-identical; non-fusable configs silently take the standard path."""
    weights = DEFAULT_WEIGHTS if weights is None else weights
    hoisted = hoisted or {}
    _NA, _TT = "NodeAffinityPriority", "TaintTolerationPriority"
    fuse_pair = ()
    if (fused and _fusable(weights, skip)
            and all(n in hoisted and hoisted[n][0] == "raw"
                    and weights.get(n) and n not in skip
                    for n in (_NA, _TT))):
        # dict order decides which name triggers the combined accumulate
        fuse_pair = tuple(n for n in weights if n in (_NA, _TT))
    total = jnp.zeros((pods.req.shape[0], nodes.allocatable.shape[0]), jnp.float32)
    for name, w in weights.items():
        if not w:
            continue
        if name in fuse_pair:
            if name == fuse_pair[0]:
                total = total + _fused_pair_normalize(
                    hoisted[_NA][1], hoisted[_TT][1], mask,
                    float(weights[_NA]), float(weights[_TT]))
            continue  # second of the pair: already accumulated
        if (name in skip and name in EMPTY_CONSTANTS
                and PRIORITY_REGISTRY[name] is _STOCK_KERNELS[name]):
            total = total + w * EMPTY_CONSTANTS[name]
        elif name in hoisted:
            kind, val, *rest = hoisted[name]
            term = val if kind == "full" else _normalize_reduce(
                val, mask, rest[0])
            total = total + w * term
        else:
            total = total + w * PRIORITY_REGISTRY[name](pods, nodes, sel, topo, mask)
    return total
