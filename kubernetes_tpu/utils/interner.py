"""String interning — the bridge between the reference's string-keyed maps
and dense integer tensor axes.

The reference scheduler compares strings everywhere (label keys/values, taint
keys, node names, image names). On TPU those comparisons become integer-id
set operations over multihot encodings, so every string universe gets a
stable int32 id space. Analogous in role to the label/topology-pair maps the
reference precomputes per cycle (``predicates/metadata.go:65``
topologyPairsMaps) — but interning is global and incremental, not per-cycle.
"""

from __future__ import annotations

from typing import Dict, Hashable, Iterable, List


class Interner:
    """Monotonic Hashable -> int32 id assignment. Ids are dense from 0 and
    never reused, so device-side multihot layouts stay valid as the universe
    grows (arrays are padded to bucketed sizes; see snapshot packing)."""

    def __init__(self) -> None:
        self._ids: Dict[Hashable, int] = {}
        self._items: List[Hashable] = []

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, item: Hashable) -> bool:
        return item in self._ids

    def intern(self, item: Hashable) -> int:
        i = self._ids.get(item)
        if i is None:
            i = len(self._items)
            self._ids[item] = i
            self._items.append(item)
        return i

    def intern_all(self, items: Iterable[Hashable]) -> List[int]:
        return [self.intern(it) for it in items]

    def lookup(self, item: Hashable) -> int:
        """-1 if unknown (unknown => cannot match anything interned)."""
        return self._ids.get(item, -1)

    def item(self, i: int) -> Hashable:
        return self._items[i]

    def items(self) -> List[Hashable]:
        return list(self._items)


def bucket_size(n: int, minimum: int = 8) -> int:
    """Round ``n`` up to the next power-of-two-ish bucket so tensor shapes
    change rarely (avoids XLA recompilation storms — SURVEY.md §7.3.6)."""
    size = max(minimum, 1)
    while size < n:
        size *= 2
    return size
