"""Operation tracing — the ``k8s.io/utils/trace`` analog the reference
wraps around every scheduling cycle (``generic_scheduler.go:185``:
``utiltrace.New(...)`` + steps + ``LogIfLong(100ms)``).

A Trace records named steps with timestamps; ``log_if_long`` emits the
step breakdown through ``logging`` only when total duration exceeds the
threshold — the cheap always-on profiler for slow cycles."""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional, Tuple

logger = logging.getLogger("kubernetes_tpu.trace")

#: the reference logs steps that took >= 50% of a (threshold/len) share;
#: we keep it simple: log everything when over threshold.
DEFAULT_THRESHOLD_S = 0.1  # LogIfLong(100*time.Millisecond)


class Trace:
    def __init__(
        self,
        name: str,
        clock: Callable[[], float] = time.monotonic,
        **fields,
    ) -> None:
        self.name = name
        self.fields = fields
        self.clock = clock
        self.start = clock()
        self.steps: List[Tuple[float, str]] = []

    def step(self, msg: str) -> None:
        self.steps.append((self.clock(), msg))

    def total_s(self) -> float:
        return self.clock() - self.start

    def format(self) -> str:
        fields = ",".join(f"{k}={v}" for k, v in self.fields.items())
        lines = [f'Trace "{self.name}" ({fields}) total={self.total_s()*1000:.1f}ms:']
        prev = self.start
        for t, msg in self.steps:
            lines.append(f"  +{(t - prev)*1000:.1f}ms {msg}")
            prev = t
        return "\n".join(lines)

    def log_if_long(self, threshold_s: float = DEFAULT_THRESHOLD_S) -> Optional[str]:
        if self.total_s() >= threshold_s:
            text = self.format()
            logger.info(text)
            return text
        return None
