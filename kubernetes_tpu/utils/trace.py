"""Operation tracing — the ``k8s.io/utils/trace`` analog.

The implementation moved to :mod:`kubernetes_tpu.obs.trace` when it grew
nested spans and the Chrome trace-event exporter (PR 3); this module
stays the stable import path for the flat utiltrace surface
(``Trace(name, clock=...)`` + ``step`` + ``log_if_long``) so existing
callers and tests keep working against the SAME class — two trace
implementations drifting apart would be an observability bug factory.
"""

from kubernetes_tpu.obs.trace import (  # noqa: F401
    DEFAULT_THRESHOLD_S,
    Span,
    Trace,
    logger,
)

__all__ = ["Trace", "Span", "DEFAULT_THRESHOLD_S", "logger"]
