"""Leveled logging — the klog analog (vendor/k8s.io/klog; component-base
logs plumbing). The reference guards expensive log paths with verbosity
checks (``klog.V(10)`` around per-node score dumps,
``generic_scheduler.go:831``); this module gives the same shape over the
stdlib ``logging`` backend:

    from kubernetes_tpu.utils.klog import V, set_verbosity, info, warning

    set_verbosity(4)            # --v=4 (cli flag / KTPU_V env)
    if V(10):                   # guard the expensive formatting
        info("scores: %s", big_tensor_dump())

Verbosity conventions follow the reference's usage: 0-2 operator-facing,
3-5 steady-state debugging, 6+ per-object trace, 10 per-(pod,node) dumps.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

logger = logging.getLogger("kubernetes_tpu")

_verbosity = 0


def set_verbosity(v: int) -> None:
    """--v flag analog (klog.InitFlags); higher = chattier."""
    global _verbosity
    _verbosity = int(v)
    if v > 0 and not logger.handlers and not logging.root.handlers:
        # klog defaults to stderr with no configuration required
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(
            "%(levelname).1s%(asctime)s.%(msecs)03d %(name)s] %(message)s",
            datefmt="%m%d %H:%M:%S",
        ))
        logger.addHandler(h)
    logger.setLevel(logging.DEBUG if v > 0 else logging.INFO)


def verbosity() -> int:
    return _verbosity


# KTPU_V env activates output immediately (the module docstring and the
# --v help advertise it; a gate that silently drops is worse than none)
if os.environ.get("KTPU_V"):
    set_verbosity(int(os.environ["KTPU_V"]))


class _Verbose:
    """klog.Verbose: truthy gate + logging methods at that level."""

    def __init__(self, enabled: bool) -> None:
        self.enabled = enabled

    def __bool__(self) -> bool:
        return self.enabled

    def info(self, msg: str, *args) -> None:
        if self.enabled:
            logger.debug(msg, *args)


def V(level: int) -> _Verbose:
    """klog.V(n): gate expensive logging on verbosity."""
    return _Verbose(_verbosity >= level)


def info(msg: str, *args) -> None:
    logger.info(msg, *args)


def warning(msg: str, *args) -> None:
    logger.warning(msg, *args)


def error(msg: str, *args) -> None:
    logger.error(msg, *args)
