from kubernetes_tpu.utils.interner import Interner  # noqa: F401
