#!/bin/bash
for i in $(seq 1 200); do
  if timeout 90 python -u -c "import jax; print(jax.devices())" >/dev/null 2>&1; then
    echo "tunnel clear after attempt $i at $(date +%T)"
    timeout 560 python -u _tpu_check.py 2>&1 | grep -v WARNING
    exit 0
  fi
  echo "attempt $i: still wedged at $(date +%T)"
  sleep 60
done
echo "never cleared"
